"""Speclint v2 fixture corpus: the whole-program dataflow framework
(call graph + worklist summaries), the U9xx range prover, the D10xx
determinism pass, the C11xx engine-coverage pass, the SARIF renderer,
the incremental cache, and the --fix autofixer.

Every pass must (a) flag its planted bug, (b) stay quiet on the safe
idiom beside it, and (c) hold its acceptance invariant on the REAL
tree: the coverage pass proves the full contract for every
``faults.SITES`` entry at baseline zero, the range prover certifies
the epoch-kernel subtractions with zero false overflow reports, and
the SARIF output validates against the 2.1.0 schema.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.tools.speclint import (
    cache as sl_cache, dataflow, driver, fixer, sarif)
from consensus_specs_tpu.tools.speclint.graph import ProjectGraph
from consensus_specs_tpu.tools.speclint.passes import (
    coverage, determinism, durability, rangeproof, uint64)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCOPED = "consensus_specs_tpu/ops/epoch_kernels.py"


def _codes(findings):
    return [f.code for f in findings]


def _write(root, rel, text):
    path = os.path.join(str(root), rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)


# ---------------------------------------------------------------------------
# Project call graph: MRO dispatch, super() chains, install_* wrapping,
# hand-vs-compiled edge parity
# ---------------------------------------------------------------------------

_HAND_BASE = (
    "class Phase0Spec:\n"
    "    fork = 'phase0'\n"
    "    def process_operations(self, state):\n"
    "        return self.helper(state)\n"
    "    def helper(self, state):\n"
    "        return state\n")
_HAND_NEXT = (
    "from consensus_specs_tpu.forks.base import Phase0Spec\n"
    "class AltairSpec(Phase0Spec):\n"
    "    def process_operations(self, state):\n"
    "        self.extra(state)\n"
    "        return super().process_operations(state)\n"
    "    def extra(self, state):\n"
    "        return state\n")
_ACCEL = (
    "def _fast_operations(spec, state):\n"
    "    return kernel(state)\n"
    "def kernel(state):\n"
    "    return state\n"
    "def install_epoch_accel(cls):\n"
    "    cls.process_operations = _fast_operations\n"
    "    setattr(cls, 'helper', kernel)\n")


def _ladder_tree(tmp_path):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/forks/base.py", _HAND_BASE)
    _write(root, "consensus_specs_tpu/forks/altair.py", _HAND_NEXT)
    _write(root, "consensus_specs_tpu/forks/compiled/base.py",
           '"""AUTO-COMPILED from specs/phase0/beacon-chain.md"""\n'
           + _HAND_BASE.replace("Phase0Spec", "CompiledPhase0Spec"))
    _write(root, "consensus_specs_tpu/forks/compiled/altair.py",
           '"""AUTO-COMPILED from specs/altair/beacon-chain.md"""\n'
           + _HAND_NEXT
           .replace("from consensus_specs_tpu.forks.base import Phase0Spec",
                    "from consensus_specs_tpu.forks.compiled.base import "
                    "CompiledPhase0Spec")
           .replace("Phase0Spec", "CompiledPhase0Spec")
           .replace("AltairSpec", "CompiledAltairSpec"))
    _write(root, "consensus_specs_tpu/ops/accel.py", _ACCEL)
    return ProjectGraph(driver.Context(str(root)))


def _edge_names(graph, cls, method):
    fn = graph.classes[cls].methods[method]
    return {c.name for c in graph.callees(fn)}


def test_graph_super_chain_resolves_across_modules(tmp_path):
    g = _ladder_tree(tmp_path)
    # AltairSpec.process_operations -> super() -> the phase0 body, plus
    # the self.extra local dispatch and the installed override
    edges = {(c.cls_name, c.name) for c in g.callees(
        g.classes["AltairSpec"].methods["process_operations"])}
    assert ("Phase0Spec", "process_operations") in edges
    assert ("AltairSpec", "extra") in edges


def test_graph_mro_resolves_inherited_method(tmp_path):
    g = _ladder_tree(tmp_path)
    # helper is defined on the base only; MRO resolution from the
    # subclass must find it
    fn = g.resolve_method("AltairSpec", "helper")
    assert fn is not None and fn.cls_name == "Phase0Spec"
    # super() dispatch starts PAST the class itself
    fn = g.resolve_method("AltairSpec", "process_operations", after=True)
    assert fn.cls_name == "Phase0Spec"


def test_graph_install_wrappers_register_overrides(tmp_path):
    g = _ladder_tree(tmp_path)
    over = {name: {f.name for f in fns}
            for name, fns in g.overrides.items()}
    assert over["process_operations"] == {"_fast_operations"}
    assert over["helper"] == {"kernel"}
    # a self.helper(...) call site therefore reaches the installed
    # kernel as well as the MRO body (the process_operations override
    # itself is an edge of that method's CALLERS, not of its body)
    edges = _edge_names(g, "Phase0Spec", "process_operations")
    assert {"helper", "kernel"} <= edges
    # and the installed wrappers are consensus roots in their own
    # right, so code only an install_* override reaches is still
    # analyzed by the determinism pass
    root_names = {n for _, n in determinism.consensus_roots(g)}
    assert "<installed>.process_operations" in root_names


def test_graph_hand_and_compiled_twins_resolve_identically(tmp_path):
    """Satellite acceptance: the same dispatch shapes (MRO, super()
    chain, install wrapping) must produce isomorphic edges for the
    hand ladder and the compiled ladder."""
    g = _ladder_tree(tmp_path)

    def shape(cls):
        out = {}
        for m in g.classes[cls].methods:
            out[m] = sorted(
                (c.cls_name or "", c.name) for c in
                g.callees(g.classes[cls].methods[m]))
        return out

    def strip(d):
        return {m: [(c.replace("Compiled", ""), n) for c, n in v]
                for m, v in d.items()}

    assert strip(shape("AltairSpec")) == strip(shape("CompiledAltairSpec"))
    assert strip(shape("Phase0Spec")) == strip(shape("CompiledPhase0Spec"))


def test_graph_compiled_provenance_parsed(tmp_path):
    g = _ladder_tree(tmp_path)
    mod = g.modules["consensus_specs_tpu/forks/compiled/altair.py"]
    assert mod.provenance == "specs/altair/beacon-chain.md"
    assert g.modules["consensus_specs_tpu/forks/altair.py"].provenance \
        is None


def test_graph_lazy_module_alias_edges(tmp_path):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/ops/a.py",
           "def entry(x):\n"
           "    from consensus_specs_tpu.ops import b\n"
           "    return b.work(x)\n")
    _write(root, "consensus_specs_tpu/ops/b.py",
           "def work(x):\n    return x\n")
    g = ProjectGraph(driver.Context(str(root)))
    fn = g.modules["consensus_specs_tpu/ops/a.py"].funcs["entry"]
    assert {c.qname for c in g.callees(fn)} \
        == {"consensus_specs_tpu/ops/b.py::work"}


def test_dataflow_worklist_converges():
    """Literal facts propagate through a three-deep call chain and the
    solver stops at the fixed point."""
    edges = {"a": {"b"}, "b": {"c"}, "c": set()}
    base = {"c": {"seed"}, "b": set(), "a": set()}

    def transfer(fn, get):
        out = set(base[fn])
        for callee in edges[fn]:
            out |= get(callee) or set()
        return frozenset(out)

    out = dataflow.solve(["a", "b", "c"], lambda f: edges[f], transfer)
    assert out["a"] == {"seed"}


# ---------------------------------------------------------------------------
# U9xx range prover
# ---------------------------------------------------------------------------

def _verdicts(src):
    [(fn, fr)] = rangeproof.analyze_source(SCOPED, src)
    return {lineno: v for (lineno, _c), (v, _r)
            in fr.sub_verdicts.items()}


def test_ranges_x_minus_x_safe():
    assert _verdicts("def f(seq):\n"
                     "    b = u64_column(seq)\n"
                     "    return b - b\n") == {3: "safe"}


def test_ranges_division_chain_safe():
    """a - a // q with q >= 1: the relational chain, not intervals."""
    src = ("# speclint: invariant: q >= 1\n"
           "def f(b, q):\n"
           "    p = b // q\n"
           "    return b - p\n")
    assert _verdicts(src) == {4: "safe"}


def test_ranges_multiplication_needs_guard_discharge():
    """BRPE * b >= b only holds when the multiply itself cannot wrap —
    the guarded-by-caller pragma (or a _guard call) is the license."""
    body = ("def f(b, brpe, q):\n"
            "    p = b // q\n"
            "    return brpe * b - p\n")
    inv = ("# speclint: invariant: brpe >= 1\n"
           "# speclint: invariant: q >= 1\n")
    assert _verdicts(inv + body)[5] == "unknown"
    pragma = "# speclint: guarded-by-caller (bounded)\n"
    assert _verdicts(pragma + inv + body)[6] == "safe"


def test_ranges_subscript_preserves_relation():
    src = ("# speclint: invariant: q >= 1\n"
           "def f(b, q, idx):\n"
           "    p = b // q\n"
           "    return b[idx] - p[idx]\n")
    assert _verdicts(src) == {4: "safe"}
    # a DIFFERENT index on each side must NOT inherit the relation
    src2 = ("# speclint: invariant: q >= 1\n"
            "def f(b, q, i, j):\n"
            "    p = b // q\n"
            "    return b[i] - p[j]\n")
    assert _verdicts(src2) == {4: "unknown"}


def test_ranges_rebinding_kills_relation():
    src = ("# speclint: invariant: q >= 1\n"
           "def f(b, q, seq):\n"
           "    p = b // q\n"
           "    b = u64_column(seq)\n"
           "    return b - p\n")
    assert _verdicts(src) == {5: "unknown"}


def test_ranges_interval_proof_and_overflow():
    safe = ("# speclint: invariant: a >= 1000\n"
            "# speclint: invariant: b <= 10\n"
            "def f(a, b):\n"
            "    return a - b\n")
    assert _verdicts(safe) == {4: "safe"}
    bad = ("# speclint: invariant: a <= 10\n"
           "# speclint: invariant: b >= 1000\n"
           "def f(a, b):\n"
           "    return a - b\n")
    assert _verdicts(bad) == {4: "overflow"}
    assert "U901" in _codes(rangeproof.check_source(SCOPED, bad))


def test_ranges_invariant_applies_to_opaque_assignment():
    """`prq = int(spec.X)` is opaque; the declared invariant still
    narrows it — the real epoch-kernel shape."""
    src = ("def f(spec, b):\n"
           "    # speclint: invariant: prq >= 1\n"
           "    prq = int(spec.PROPOSER_REWARD_QUOTIENT)\n"
           "    p = b // prq\n"
           "    return b - p\n")
    assert _verdicts(src) == {5: "safe"}


def test_ranges_invariant_errors_are_u902():
    for inv in ("# speclint: invariant: a >=\n",
                "# speclint: invariant: a + b\n",
                "# speclint: invariant: a <= b\n",
                "# speclint: invariant: 5 <= a <= 3\n"):
        src = inv + "def f(a, b):\n    return a\n"
        assert _codes(rangeproof.check_source(SCOPED, src)) == ["U902"], inv
    ok = ("# speclint: invariant: 1 <= a <= MAX_EFFECTIVE_BALANCE\n"
          "def f(a, b):\n    return a\n")
    assert rangeproof.check_source(SCOPED, ok) == []


def test_ranges_redundant_noqa_is_u903():
    src = ("def f(b):\n"
           "    return b - b  # noqa: U101\n")
    findings = rangeproof.check_source(SCOPED, src)
    assert _codes(findings) == ["U903"]
    # a noqa on a genuinely unprovable subtraction is NOT redundant
    src2 = ("def f(b, p):\n"
            "    return b - p  # noqa: U101\n")
    assert rangeproof.check_source(SCOPED, src2) == []


def test_uint64_u101_discharged_by_prover():
    """The integration the pragmas were demoted for: a taint-flagged
    subtraction the prover certifies no longer fires U101."""
    src = ("# speclint: invariant: q >= 1\n"
           "def f(seq, q):\n"
           "    b = u64_column(seq)\n"
           "    p = b // q\n"
           "    return b - p\n")
    assert "U101" not in _codes(uint64.check_source(SCOPED, src))
    unproven = ("def f(seq, q):\n"
                "    b = u64_column(seq)\n"
                "    p = b // q\n"
                "    return b - p\n")   # q >= 1 NOT declared
    assert "U101" in _codes(uint64.check_source(SCOPED, unproven))


def test_real_epoch_kernel_subtractions_proven():
    """Acceptance: the two historically noqa'd epoch-kernel
    subtractions carry machine-checked proofs, their pragmas are gone,
    and the whole scoped tree has zero false overflow reports."""
    with open(os.path.join(REPO, SCOPED)) as f:
        text = f.read()
    assert "noqa: U101" not in text, \
        "the safe-subtraction pragmas were supposed to be demoted"
    results = rangeproof.analyze_source(SCOPED, text)
    proven = {
        (fn.name, lineno): verdict
        for fn, fr in results
        for (lineno, _c), (verdict, _r) in fr.sub_verdicts.items()}
    assert any(fn == "phase0_inactivity_kernel" and v == "safe"
               for (fn, _), v in proven.items())
    assert any(fn == "_phase0_rewards_and_penalties" and v == "safe"
               for (fn, _), v in proven.items())
    ctx = driver.Context(REPO)
    findings = [f for rel in ctx.py_files if rangeproof.in_scope(rel)
                for f in rangeproof.check_source(rel, ctx.source(rel))]
    assert findings == [], \
        f"U9xx must be baseline-zero on the repo: {findings}"


# ---------------------------------------------------------------------------
# D10xx determinism pass
# ---------------------------------------------------------------------------

def _det_tree(tmp_path, helper_body, helper_name="work"):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/forks/foo.py",
           "from consensus_specs_tpu.ops import eng\n"
           "class FooSpec:\n"
           "    def process_thing(self, state):\n"
           f"        return eng.{helper_name}(state)\n")
    _write(root, "consensus_specs_tpu/ops/eng.py", helper_body)
    return driver.Context(str(root))


def test_determinism_flags_set_order_escape(tmp_path):
    ctx = _det_tree(tmp_path,
                    "def work(state):\n"
                    "    s = set(state)\n"
                    "    return list(s)\n")
    findings = determinism.run(ctx)
    assert _codes(findings) == ["D1001"]
    assert "reachable from FooSpec.process_thing" in findings[0].message


def test_determinism_sorted_and_folds_exempt(tmp_path):
    ctx = _det_tree(tmp_path,
                    "def work(state):\n"
                    "    s = set(state)\n"
                    "    total = 0\n"
                    "    for x in s:\n"
                    "        total += x\n"       # order-insensitive fold
                    "    return sorted(s), total\n")
    assert determinism.run(ctx) == []


def test_determinism_collective_folds_exempt(tmp_path):
    """A sink whose value flows DIRECTLY into an order-insensitive fold
    is exempt: host folds (``sum(list(s))``) and the mesh collectives
    (``psum``/``all_gather`` — modular addition over a fixed axis /
    gathered by mesh index, never by arrival order)."""
    ctx = _det_tree(tmp_path,
                    "import jax\n"
                    "import numpy as np\n"
                    "def work(state):\n"
                    "    s = set(state)\n"
                    "    a = sum(list(s))\n"
                    "    b = jax.lax.psum(np.fromiter(s, np.uint64),\n"
                    "                     'validators')\n"
                    "    c = jax.lax.all_gather(np.asarray(list(s)),\n"
                    "                           'validators')\n"
                    "    return a, b, c\n")
    assert determinism.run(ctx) == []


def test_determinism_fold_exemption_is_direct_only(tmp_path):
    """The exemption stops at statement boundaries: materializing the
    unordered list FIRST and folding later still leaks the order (the
    intermediate list is a consensus-visible value)."""
    ctx = _det_tree(tmp_path,
                    "import jax\n"
                    "def work(state):\n"
                    "    s = set(state)\n"
                    "    items = list(s)\n"
                    "    return jax.lax.psum(items, 'v')\n")
    assert _codes(determinism.run(ctx)) == ["D1001"]


def test_determinism_reports_in_parallel_package(tmp_path):
    """The mesh engine (``consensus_specs_tpu/parallel/``) produces
    consensus-visible results: findings there must report."""
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/forks/foo.py",
           "from consensus_specs_tpu.parallel import eng\n"
           "class FooSpec:\n"
           "    def process_thing(self, state):\n"
           "        return eng.work(state)\n")
    _write(root, "consensus_specs_tpu/parallel/eng.py",
           "def work(state):\n"
           "    return state * 0.5\n")
    ctx = driver.Context(str(root))
    findings = determinism.run(ctx)
    assert _codes(findings) == ["D1002"]
    assert findings[0].path == "consensus_specs_tpu/parallel/eng.py"


def test_determinism_flags_order_sensitive_set_loop(tmp_path):
    ctx = _det_tree(tmp_path,
                    "def work(state):\n"
                    "    out = []\n"
                    "    for x in set(state):\n"
                    "        out.append(x)\n"
                    "    return out\n")
    assert _codes(determinism.run(ctx)) == ["D1001"]


def test_determinism_flags_float_and_division(tmp_path):
    ctx = _det_tree(tmp_path,
                    "def work(state):\n"
                    "    half = state * 0.5\n"
                    "    return half + state / 2\n")
    assert _codes(determinism.run(ctx)) == ["D1002", "D1002"]


def test_determinism_flags_ambient_reads(tmp_path):
    ctx = _det_tree(tmp_path,
                    "import os, time, random\n"
                    "def work(state):\n"
                    "    t = time.time()\n"
                    "    r = random.random()\n"
                    "    e = os.environ.get('X')\n"
                    "    return t, r, e, state\n")
    assert _codes(determinism.run(ctx)) == ["D1003", "D1003", "D1003"]


def test_determinism_flags_id_keys_and_builtin_hash(tmp_path):
    ctx = _det_tree(tmp_path,
                    "_CACHE = {}\n"
                    "def work(state):\n"
                    "    _CACHE[id(state)] = 1\n"
                    "    return hash('x')\n")
    assert _codes(determinism.run(ctx)) == ["D1004", "D1005"]


def test_determinism_spec_hash_shadow_exempt(tmp_path):
    ctx = _det_tree(tmp_path,
                    "from consensus_specs_tpu.utils.hash_function "
                    "import hash\n"
                    "def work(state):\n"
                    "    return hash(state)\n")
    assert determinism.run(ctx) == []


def test_determinism_unreachable_code_not_flagged(tmp_path):
    """The reachability half: the same hazard in a function nothing on
    a consensus path calls stays quiet."""
    ctx = _det_tree(tmp_path,
                    "def work(state):\n"
                    "    return state\n"
                    "def bench_helper(state):\n"
                    "    import time\n"
                    "    return time.time()\n")
    assert determinism.run(ctx) == []


def test_determinism_compiled_modules_not_double_reported(tmp_path):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/forks/foo.py",
           "class FooSpec:\n"
           "    def process_thing(self, state):\n"
           "        return state / 2\n")
    _write(root, "consensus_specs_tpu/forks/compiled/foo.py",
           '"""AUTO-COMPILED from specs/foo.md"""\n'
           "class CompiledFooSpec:\n"
           "    def process_thing(self, state):\n"
           "        return state / 2\n")
    findings = determinism.run(driver.Context(str(root)))
    assert _codes(findings) == ["D1002"]
    assert findings[0].path == "consensus_specs_tpu/forks/foo.py"


def test_determinism_real_tree_clean():
    """Acceptance half of satellite 1: after the das table-key,
    env-knob and kzg integer-math fixes, the consensus surface is
    determinism-clean."""
    assert determinism.run(driver.Context(REPO)) == []


def test_determinism_flags_tuple_id_key(tmp_path):
    """D1004 catches an id() call hidden inside a tuple key."""
    ctx = _det_tree(tmp_path,
                    "CACHE = {}\n"
                    "def work(state):\n"
                    "    return CACHE.get((id(state), 4))\n")
    assert _codes(determinism.run(ctx)) == ["D1004"]


def test_determinism_flags_id_tainted_name_key(tmp_path):
    """D1004 catches the two-line shape the sim genesis cache had:
    ``key = (id(x), n)`` then ``d.get(key)``."""
    ctx = _det_tree(tmp_path,
                    "CACHE = {}\n"
                    "def work(state):\n"
                    "    key = (id(state), 4)\n"
                    "    return CACHE.get(key)\n")
    findings = determinism.run(ctx)
    assert _codes(findings) == ["D1004"]


def test_determinism_d1004_reports_in_sim_scope(tmp_path):
    """The sim package is scanned for D1004 regardless of
    consensus-root reachability — but ONLY for D1004: the harness may
    read clocks and RNG by design."""
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/sim/fixture_driver.py",
           "import time\n"
           "CACHE = {}\n"
           "def genesis(spec, n):\n"
           "    key = (id(spec), n)\n"
           "    return CACHE.get(key)\n"
           "def pacing():\n"
           "    return time.time()\n")
    findings = determinism.run(driver.Context(str(root)))
    assert _codes(findings) == ["D1004"]
    assert "sim persistence scope" in findings[0].message


def test_determinism_sim_driver_genesis_cache_clean():
    """Regression for the fixed stale-aliasing bug: the real
    ``sim/driver.py`` genesis cache keys by stable spec identity now —
    zero D1004 findings anywhere under ``consensus_specs_tpu/sim/``."""
    findings = determinism.run(driver.Context(REPO))
    assert [f for f in findings
            if f.path.startswith("consensus_specs_tpu/sim/")] == []


# ---------------------------------------------------------------------------
# R9xx durability pass
# ---------------------------------------------------------------------------

def test_durability_flags_bare_final_path_write(tmp_path):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/recovery/foo.py",
           "import json\n"
           "def dump(path, payload):\n"
           "    with open(path, 'w') as f:\n"
           "        json.dump(payload, f)\n")
    findings = durability.run(driver.Context(str(root)))
    assert _codes(findings) == ["R901"]
    assert "torn file" in findings[0].message


def test_durability_temp_rename_exempt(tmp_path):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/recovery/foo.py",
           "import os\n"
           "def dump(path, data):\n"
           "    with open(path + '.tmp', 'wb') as f:\n"
           "        f.write(data)\n"
           "        os.fsync(f.fileno())\n"
           "    os.replace(path + '.tmp', path)\n")
    assert durability.run(driver.Context(str(root))) == []


def test_durability_atomic_helper_exempt(tmp_path):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/sim/repro.py",
           "from consensus_specs_tpu.recovery.atomic import "
           "atomic_write_json\n"
           "def dump(path, payload):\n"
           "    atomic_write_json(path, payload)\n")
    assert durability.run(driver.Context(str(root))) == []


def test_durability_fsynced_class_journal_exempt(tmp_path):
    """An append-mode journal certified by the fsync in a SIBLING
    method of the same class (the write-ahead journal's shape)."""
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/recovery/journal2.py",
           "import os\n"
           "class J:\n"
           "    def __init__(self, path):\n"
           "        self._f = open(path, 'ab')\n"
           "    def commit(self):\n"
           "        os.fsync(self._f.fileno())\n")
    assert durability.run(driver.Context(str(root))) == []


def test_durability_str_replace_does_not_exempt(tmp_path):
    """Only ``os.replace``/``os.rename``/``os.fsync`` certify the
    discipline — an ordinary str.replace filename slug next to a bare
    write must still flag."""
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/recovery/foo.py",
           "def dump(site, data):\n"
           "    path = site.replace('.', '-') + '.json'\n"
           "    with open(path, 'w') as f:\n"
           "        f.write(data)\n")
    assert _codes(durability.run(driver.Context(str(root)))) == ["R901"]


def test_durability_out_of_scope_quiet(tmp_path):
    """The same bare write outside the persistence scopes is not this
    pass's business."""
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/ops/foo.py",
           "def dump(path, data):\n"
           "    with open(path, 'w') as f:\n"
           "        f.write(data)\n")
    assert durability.run(driver.Context(str(root))) == []


def test_durability_real_tree_clean():
    """Acceptance: after the repro/gen_runner conversions to
    recovery/atomic.py, the persistence scopes carry zero bare
    final-path writes."""
    assert durability.run(driver.Context(REPO)) == []


# ---------------------------------------------------------------------------
# C11xx engine-coverage pass
# ---------------------------------------------------------------------------

_FIXTURE_FAULTS = (
    "class InjectedFault(BaseException):\n"
    "    pass\n"
    "SITES = (\n"
    "    'demo.dispatch',\n"
    ")\n"
    "SITE_SWITCHES = {\n"
    "    'demo.': 'CS_TPU_DEMO',\n"
    "}\n"
    "def check(site):\n    pass\n"
    "def count_fallback(series, exc=None, organic='guard', site=None):\n"
    "    pass\n")

# the epoch shape: the literal flows through a shared helper's
# parameter, so proving the contract REQUIRES the interprocedural
# literal-flow solve
_FIXTURE_ENGINE = (
    "from consensus_specs_tpu import faults, supervisor\n"
    "def _supervised(spec, state, site, fast_fn):\n"
    "    if not supervisor.admit(site):\n"
    "        return False\n"
    "    try:\n"
    "        faults.check(site)\n"
    "        fast_fn(state)\n"
    "    except faults.InjectedFault as exc:\n"
    "        faults.count_fallback(_F, exc, site=site)\n"
    "        return False\n"
    "    return True\n"
    "def try_demo(spec, state):\n"
    "    return _supervised(spec, state, 'demo.dispatch', kernel)\n"
    "def kernel(state):\n"
    "    return state\n")
_FIXTURE_TEST = (
    "def test_demo_differential():\n"
    "    assert 'demo.dispatch'\n")
_FIXTURE_WORKFLOW = (
    "jobs:\n"
    "  off-leg:\n"
    "    steps:\n"
    "      - run: CS_TPU_DEMO=0 python -m pytest tests/ -q\n")


def _cov_tree(tmp_path, *, faults_text=_FIXTURE_FAULTS,
              engine=_FIXTURE_ENGINE, test=_FIXTURE_TEST,
              workflow=_FIXTURE_WORKFLOW):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/faults.py", faults_text)
    if engine is not None:
        _write(root, "consensus_specs_tpu/ops/eng.py", engine)
    if test is not None:
        _write(root, "tests/test_demo.py", test)
    if workflow is not None:
        _write(root, ".github/workflows/run-tests.yml", workflow)
    return str(root)


def test_coverage_full_contract_is_clean(tmp_path):
    assert coverage.check_tree(_cov_tree(tmp_path)) == []


def test_coverage_missing_each_leg_fires(tmp_path):
    # no dispatch at all: C1101/C1102/C1103/C1104 in one shot
    codes = _codes(coverage.check_tree(_cov_tree(
        tmp_path, engine="def unrelated():\n    pass\n")))
    assert {"C1101", "C1102", "C1103", "C1104"} <= set(codes)

    # counted fallback dropped
    no_count = _FIXTURE_ENGINE.replace(
        "        faults.count_fallback(_F, exc, site=site)\n", "")
    codes = _codes(coverage.check_tree(
        _cov_tree(tmp_path / "b", engine=no_count)))
    assert codes == ["C1102"]

    # supervisor gate dropped
    no_admit = _FIXTURE_ENGINE.replace(
        "    if not supervisor.admit(site):\n"
        "        return False\n", "    pass\n")
    codes = _codes(coverage.check_tree(
        _cov_tree(tmp_path / "c", engine=no_admit)))
    assert codes == ["C1103"]

    # fallback handler dropped (count moved out of a handler)
    no_handler = (
        "from consensus_specs_tpu import faults, supervisor\n"
        "def try_demo(spec, state):\n"
        "    site = 'demo.dispatch'\n"
        "    supervisor.admit(site)\n"
        "    faults.check(site)\n"
        "    faults.count_fallback(_F, None, site=site)\n")
    codes = _codes(coverage.check_tree(
        _cov_tree(tmp_path / "d", engine=no_handler)))
    assert codes == ["C1104"]

    # differential test reference dropped
    codes = _codes(coverage.check_tree(
        _cov_tree(tmp_path / "e", test="def test_other():\n    pass\n")))
    assert codes == ["C1105"]

    # CI off-leg dropped
    codes = _codes(coverage.check_tree(_cov_tree(
        tmp_path / "f",
        workflow=_FIXTURE_WORKFLOW.replace("CS_TPU_DEMO=0", ""))))
    assert codes == ["C1106"]


def test_coverage_site_without_switch_family(tmp_path):
    faults_text = _FIXTURE_FAULTS.replace(
        "    'demo.': 'CS_TPU_DEMO',\n", "    'other.': 'CS_TPU_OTHER',\n")
    codes = _codes(coverage.check_tree(
        _cov_tree(tmp_path, faults_text=faults_text)))
    assert "C1100" in codes


def test_coverage_unregistered_site_is_c1107(tmp_path):
    rogue = _FIXTURE_ENGINE + (
        "def try_rogue(spec, state):\n"
        "    return _supervised(spec, state, 'rogue.site', kernel)\n")
    findings = coverage.check_tree(_cov_tree(tmp_path, engine=rogue))
    assert [f.code for f in findings] == ["C1107"]
    assert "rogue.site" in findings[0].message
    assert findings[0].path == "consensus_specs_tpu/ops/eng.py"


def test_coverage_findings_anchor_at_sites_tuple(tmp_path):
    findings = coverage.check_tree(_cov_tree(
        tmp_path, test="def test_other():\n    pass\n"))
    (f,) = findings
    assert f.path == "consensus_specs_tpu/faults.py"
    assert f.line == 4      # the 'demo.dispatch' tuple entry line


def test_coverage_absent_faults_module_is_quiet(tmp_path):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/ops/eng.py", "x = 1\n")
    assert coverage.check_tree(str(root)) == []


def test_coverage_real_tree_baseline_zero():
    """THE acceptance criterion: every faults.SITES entry proves the
    full contract — dispatch + counted fallback + supervisor gate +
    degradation handler + differential reference + CI off-leg — on the
    real tree, with nothing noqa'd or baselined."""
    findings = coverage.run(driver.Context(REPO))
    assert findings == [], "\n".join(f.render() for f in findings)
    # and non-vacuously: the solver really resolved every site
    from consensus_specs_tpu import faults
    graph = driver.Context(REPO).project_graph()
    site_facts, _ = coverage.solve_site_facts(graph)
    for site in faults.SITES:
        assert {"check", "count", "admit", "handler"} \
            <= site_facts.get(site, set()), site


# ---------------------------------------------------------------------------
# SARIF output
# ---------------------------------------------------------------------------

def test_sarif_real_run_validates():
    ctx = driver.Context(REPO)
    findings = driver.run_passes(ctx)
    baseline = driver.load_baseline(os.path.join(REPO,
                                                 driver.BASELINE_NAME))
    new, baselined, _ = driver.apply_baseline(findings, baseline)
    log = sarif.to_sarif(new, baselined)
    assert log["version"] == "2.1.0"
    assert sarif.validate(log) == []
    # the recorded debt must surface as unchanged results (plus any
    # `absent` markers for baseline keys whose findings are fixed)
    states = {r["baselineState"] for r in log["runs"][0]["results"]}
    assert states <= {"new", "unchanged", "absent"} \
        and "unchanged" in states
    rule_ids = {r["id"] for r in log["runs"][0]["tool"]["driver"]["rules"]}
    assert {r["ruleId"] for r in log["runs"][0]["results"]} <= rule_ids


def test_sarif_validator_rejects_malformed():
    assert sarif.validate({"version": "1.0", "runs": []}) != []
    assert sarif.validate({"version": "2.1.0"}) != []
    bad = sarif.to_sarif([], [])
    bad["runs"][0]["results"] = [{"message": {}}]
    assert sarif.validate(bad) != []


def test_sarif_driver_format(tmp_path, capsys):
    root = tmp_path / "repo"
    _write(root, SCOPED,
           "def f(seq):\n"
           "    b = u64_column(seq)\n"
           "    p = u64_column(seq)\n"
           "    return b - p\n")
    rc = driver.main([str(root), "--passes", "uint64", "--format",
                      "sarif", "--no-baseline"])
    assert rc == 1
    log = json.loads(capsys.readouterr().out)
    assert sarif.validate(log) == []
    (result,) = log["runs"][0]["results"]
    assert result["ruleId"] == "U101"
    assert result["baselineState"] == "new"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == SCOPED
    assert loc["region"]["startLine"] == 4


# ---------------------------------------------------------------------------
# Incremental cache
# ---------------------------------------------------------------------------

def _cache_stats(root, *args):
    c = sl_cache.AnalysisCache(os.path.join(root, sl_cache.CACHE_NAME),
                               "salt")
    return c


def test_cache_warm_run_reuses_everything(tmp_path):
    root = tmp_path / "repo"
    _write(root, SCOPED,
           "def f(seq):\n"
           "    b = u64_column(seq)\n"
           "    p = u64_column(seq)\n"
           "    return b - p\n")
    assert driver.main([str(root), "--no-baseline"]) == 1
    ctx = driver.Context(str(root))
    cache = sl_cache.AnalysisCache(
        os.path.join(str(root), sl_cache.CACHE_NAME),
        driver._pass_salt())
    findings = driver.run_passes(ctx, cache=cache)
    assert cache.stats["file_misses"] == 0
    assert cache.stats["tree_misses"] == 0
    assert [f.code for f in findings] == ["U101"]


def test_cache_invalidates_on_edit_and_salt(tmp_path):
    root = tmp_path / "repo"
    _write(root, SCOPED, "def f(seq):\n    return u64_column(seq)\n")
    _write(root, "consensus_specs_tpu/utils/other.py", "x = 1\n")
    assert driver.main([str(root)]) == 0
    # edit ONE file: only its entries miss; the other file stays warm
    _write(root, SCOPED,
           "def f(seq):\n"
           "    b = u64_column(seq)\n"
           "    p = u64_column(seq)\n"
           "    return b - p\n")
    ctx = driver.Context(str(root))
    cache = sl_cache.AnalysisCache(
        os.path.join(str(root), sl_cache.CACHE_NAME),
        driver._pass_salt())
    findings = driver.run_passes(ctx, cache=cache)
    assert [f.code for f in findings] == ["U101"]
    assert cache.stats["file_hits"] > 0          # the untouched file
    assert cache.stats["file_misses"] > 0        # the edited one
    assert cache.stats["tree_misses"] > 0        # tree fingerprint moved
    # a salt change (pass version bump) drops the whole store
    stale = sl_cache.AnalysisCache(
        os.path.join(str(root), sl_cache.CACHE_NAME), "other-salt")
    assert stale.get_file(SCOPED, ctx.sha(SCOPED), "uint64") is None


def test_cache_findings_roundtrip_suppression(tmp_path):
    """Cached findings are pre-noqa; the driver re-applies suppression
    after retrieval, so a cache hit behaves exactly like a fresh run."""
    root = tmp_path / "repo"
    _write(root, SCOPED,
           "def f(seq):\n"
           "    b = u64_column(seq)\n"
           "    p = u64_column(seq)\n"
           "    return b - p  # noqa: U101\n")
    assert driver.main([str(root), "--no-baseline"]) == 0
    assert driver.main([str(root), "--no-baseline"]) == 0   # warm


# ---------------------------------------------------------------------------
# --fix autofixer
# ---------------------------------------------------------------------------

def test_fix_u103_adds_dtype():
    src = ("import numpy as np\n"
           "def f(mask):\n"
           "    n = mask.sum()\n"
           "    k = mask.sum(dtype=np.int64)\n")
    fixed, n = fixer.fix_u103(SCOPED, src)
    assert n == 1
    assert "mask.sum(dtype=np.int64)\n    k" in fixed
    # idempotent + out-of-scope untouched
    assert fixer.fix_u103(SCOPED, fixed) == (fixed, 0)
    assert fixer.fix_u103("consensus_specs_tpu/sim/x.py", src)[1] == 0


def test_fix_noqa_normalizes_real_comments_only():
    src = ("x = 1  #noqa:u101,j203\n"
           "y = 2  # NOQA\n"
           'DOC = """example: #noqa:u101 stays as-is"""\n')
    fixed, n = fixer.fix_noqa(src)
    assert "x = 1  # noqa: U101, J203\n" in fixed
    assert "y = 2  # noqa\n" in fixed
    assert '#noqa:u101 stays as-is' in fixed      # docstring untouched
    assert n == 2
    assert fixer.fix_noqa(fixed) == (fixed, 0)    # idempotent


def test_fix_noqa_keeps_justification_text():
    src = "b = a - c  # noqa: u101 with a bound argument\n"
    fixed, n = fixer.fix_noqa(src)
    assert fixed == "b = a - c  # noqa: U101 with a bound argument\n"
    assert n == 1
    # an unparsable code list is left alone, not mangled
    weird = "x = 1  # noqa: D100x\n"
    assert fixer.fix_noqa(weird) == (weird, 0)


def test_fix_import_hoist_removes_redundant_only():
    src = ("import hashlib\n"
           "def f(x):\n"
           "    import hashlib\n"
           "    return hashlib.sha256(x)\n"
           "def g(x):\n"
           "    import secrets\n"        # NOT at top: deliberate lazy
           "    return secrets.token_bytes(4)\n")
    fixed, n = fixer.fix_import_hoist("m.py", src)
    assert n == 1
    assert fixed.count("import hashlib") == 1
    assert "    import secrets" in fixed          # lazy import kept
    assert fixer.fix_import_hoist("m.py", fixed) == (fixed, 0)


def test_fix_tree_end_to_end(tmp_path):
    root = tmp_path / "repo"
    messy = ("import numpy as np\n"
             "def f(mask):\n"
             "    import numpy as np  # kept: aliased, not plain\n"
             "    return mask.sum()  #noqa:u103\n")
    _write(root, SCOPED, messy)
    _write(root, "tests/test_fixture.py", "S = 'x = 1  #noqa:u101'\n")
    rc = driver.main([str(root), "--fix"])
    assert rc == 0
    with open(os.path.join(str(root), SCOPED)) as f:
        fixed = f.read()
    assert "mask.sum(dtype=np.int64)  # noqa: U103" in fixed
    # tests/ fixtures excluded
    with open(os.path.join(str(root), "tests/test_fixture.py")) as f:
        assert f.read() == "S = 'x = 1  #noqa:u101'\n"
    # second --fix is a no-op
    driver.main([str(root), "--fix"])
    with open(os.path.join(str(root), SCOPED)) as f:
        assert f.read() == fixed


def test_fix_is_noop_on_real_tree():
    """The repo itself carries no mechanically-fixable debt (and --fix
    must never churn it)."""
    from consensus_specs_tpu.tools.speclint.astutil import is_generated
    ctx = driver.Context(REPO)
    for rel in ctx.py_files:
        if rel.startswith(fixer._FIX_EXCLUDE):
            continue
        text = ctx.source(rel)
        if is_generated(text):
            continue
        fixed, _counts = fixer.fix_text(rel, text)
        assert fixed == text, f"--fix would modify {rel}"


# ---------------------------------------------------------------------------
# driver surface
# ---------------------------------------------------------------------------

def test_range_verdicts_cli(capsys):
    assert driver.main([REPO, "--range-verdicts"]) == 0
    out = capsys.readouterr().out
    assert "phase0_inactivity_kernel" in out
    assert "[safe]" in out


def test_baseline_guard_matches_conftest_contract():
    """The checked-in ratchet file satisfies the conftest deflake
    guard's invariants (sorted, deduped, positive counts)."""
    path = os.path.join(REPO, "speclint_baseline.json")
    with open(path) as f:
        raw = f.read()
    pairs = json.loads(
        raw, object_pairs_hook=lambda ps: ps)
    # top-level: comment + counts
    counts = dict(pairs)["counts"]
    keys = [k for k, _ in counts]
    assert keys == sorted(keys)
    assert len(keys) == len(set(keys))
    assert all(isinstance(v, int) and v >= 1 for _, v in counts)


# ---------------------------------------------------------------------------
# review regressions
# ---------------------------------------------------------------------------

def test_ranges_inplace_mutation_kills_stale_interval():
    """Review regression: `pen[idx] += big` (and np.add.at) must
    invalidate pen's abstract value — a later `rewards - pen` was
    falsely proven safe against pen's stale zeros() interval."""
    src = ("import numpy as np\n"
           "def f(seq, idx, big):\n"
           "    rewards = u64_column(seq)\n"
           "    pen = np.zeros(4, dtype=np.uint64)\n"
           "    pen[idx] += big\n"
           "    return rewards - pen\n")
    assert _verdicts(src)[6] == "unknown"
    assert "U101" in _codes(uint64.check_source(SCOPED, src))
    scatter = ("import numpy as np\n"
               "def f(seq, idx, big):\n"
               "    rewards = u64_column(seq)\n"
               "    pen = np.zeros(4, dtype=np.uint64)\n"
               "    np.add.at(pen, idx, big)\n"
               "    return rewards - pen\n")
    assert _verdicts(scatter)[6] == "unknown"
    # while an UNtouched zeros() interval still proves safe
    clean = ("import numpy as np\n"
             "def f(seq):\n"
             "    rewards = u64_column(seq)\n"
             "    pen = np.zeros(4, dtype=np.uint64)\n"
             "    return rewards - pen\n")
    assert _verdicts(clean)[5] == "safe"


def test_coverage_handler_in_caller_of_literal_dispatch(tmp_path):
    """Review regression: an engine whose helper dispatches the site
    literal INLINE (no site parameter) with the fallback handler in
    the caller must still prove the C1104 leg."""
    engine = (
        "from consensus_specs_tpu import faults, supervisor\n"
        "def _dispatch(state):\n"
        "    supervisor.admit('demo.dispatch')\n"
        "    faults.check('demo.dispatch')\n"
        "    return state\n"
        "def entry(state):\n"
        "    try:\n"
        "        return _dispatch(state)\n"
        "    except faults.InjectedFault as exc:\n"
        "        faults.count_fallback(_F, exc, site='demo.dispatch')\n"
        "        return state\n")
    assert coverage.check_tree(_cov_tree(tmp_path, engine=engine)) == []


def test_fix_import_hoist_never_empties_a_body():
    """Review regression: deleting a function's only statement (or all
    of them) must not emit an unparsable empty body."""
    import ast as _ast
    sole = ("import os\n"
            "def probe():\n"
            "    import os\n")
    fixed, n = fixer.fix_import_hoist("m.py", sole)
    _ast.parse(fixed)
    assert n == 0 and "def probe():" in fixed
    double = ("import os\n"
              "import sys\n"
              "def probe():\n"
              "    import os\n"
              "    import sys\n")
    fixed, n = fixer.fix_import_hoist("m.py", double)
    _ast.parse(fixed)
    assert n == 1     # one deleted, one kept so the body stays valid


def test_ranges_memo_shared_between_passes(tmp_path):
    """Review cleanup: one FunctionRanges per function per run — the
    uint64 discharge and the U9xx pass share the Context memo."""
    root = tmp_path / "repo"
    _write(root, SCOPED,
           "def f(seq):\n"
           "    b = u64_column(seq)\n"
           "    return b - b\n")
    ctx = driver.Context(str(root))
    ctx.ranges_memo = {}
    assert uint64.check_file(ctx, SCOPED) == []
    assert rangeproof.check_file(ctx, SCOPED) == []
    assert len(ctx.ranges_memo) == 1      # analyzed once, served twice


# ---------------------------------------------------------------------------
# E12xx effects pass: commit-scope proofs, shard safety, write ordering
# ---------------------------------------------------------------------------

import ast as _e_ast
import shutil
import subprocess
import time

from consensus_specs_tpu.tools.speclint import effects as fx
from consensus_specs_tpu.tools.speclint.passes import effects as effects_pass

_FX_ARRAYS = (
    "def flush(state):\n    pass\n"
    "def commit_scope(state):\n    pass\n"
    "def fork_state(state):\n    pass\n")

_FX_ENGINE_GUARDED = (
    "from consensus_specs_tpu.state import arrays as state_arrays\n"
    "class DemoSpec:\n"
    "    def process_slots(self, state):\n"
    "        with state_arrays.commit_scope(state):\n"
    "            self.process_epoch(state)\n"
    "    def process_epoch(self, state):\n"
    "        self.process_rewards(state)\n"
    "    def process_rewards(self, state):\n"
    "        if try_fast(self, state):\n"
    "            return\n"
    "        self.apply(state)\n"
    "    def apply(self, state):\n"
    "        state.balances[0] += 1\n"
    "def try_fast(spec, state):\n"
    "    state_arrays.flush(state)\n"
    "    return False\n")


def _fx_tree(tmp_path, engine=_FX_ENGINE_GUARDED, arrays_src=_FX_ARRAYS,
             extra=()):
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/state/arrays.py", arrays_src)
    _write(root, "consensus_specs_tpu/forks/demo.py", engine)
    for rel, text in extra:
        _write(root, rel, text)
    return str(root)


def test_e1201_guarded_ladder_is_clean(tmp_path):
    assert effects_pass.check_tree(_fx_tree(tmp_path)) == []


def test_e1201_unguarded_write_escapes_scope(tmp_path):
    engine = _FX_ENGINE_GUARDED.replace(
        "        if try_fast(self, state):\n"
        "            return\n", "")
    findings = effects_pass.check_tree(_fx_tree(tmp_path, engine=engine))
    assert [f.code for f in findings] == ["E1201"]
    # anchored at the write site, deep in the interprocedural closure
    assert findings[0].path == "consensus_specs_tpu/forks/demo.py"
    assert "balances" in findings[0].message


def test_e1201_flush_in_callee_guards_later_write(tmp_path):
    # the guard flows through a transitively-flushing callee: try_fast
    # flushes inside _supervised-style helpers two levels down
    engine = _FX_ENGINE_GUARDED.replace(
        "def try_fast(spec, state):\n"
        "    state_arrays.flush(state)\n"
        "    return False\n",
        "def try_fast(spec, state):\n"
        "    return _inner(state)\n"
        "def _inner(state):\n"
        "    state_arrays.flush(state)\n"
        "    return False\n")
    assert effects_pass.check_tree(_fx_tree(tmp_path, engine=engine)) == []


def test_e1201_write_before_flush_still_fires(tmp_path):
    engine = _FX_ENGINE_GUARDED.replace(
        "    def process_rewards(self, state):\n"
        "        if try_fast(self, state):\n"
        "            return\n"
        "        self.apply(state)\n",
        "    def process_rewards(self, state):\n"
        "        self.apply(state)\n"
        "        try_fast(self, state)\n")
    findings = effects_pass.check_tree(_fx_tree(tmp_path, engine=engine))
    assert [f.code for f in findings] == ["E1201"]


def test_e1201_noqa_suppresses_through_driver(tmp_path):
    engine = _FX_ENGINE_GUARDED.replace(
        "        if try_fast(self, state):\n"
        "            return\n", "").replace(
        "        state.balances[0] += 1\n",
        "        state.balances[0] += 1  # noqa: E1201\n")
    root = _fx_tree(tmp_path, engine=engine)
    assert driver.main([root, "--passes", "effects", "--no-baseline"]) == 0


def test_e1201_opted_out_class_excluded(tmp_path):
    engine = _FX_ENGINE_GUARDED + (
        "class CustodySpec(DemoSpec):\n"
        "    _defer_epoch_commits = False\n"
        "    def process_epoch(self, state):\n"
        "        state.balances[0] += 2\n")
    assert effects_pass.check_tree(_fx_tree(tmp_path, engine=engine)) == []


def test_e1202_fork_state_in_scope(tmp_path):
    engine = _FX_ENGINE_GUARDED.replace(
        "    def process_epoch(self, state):\n",
        "    def process_epoch(self, state):\n"
        "        state_arrays.fork_state(state)\n")
    findings = effects_pass.check_tree(_fx_tree(tmp_path, engine=engine))
    assert [f.code for f in findings] == ["E1202"]


def test_e1203_checkpoint_save_in_scope(tmp_path):
    engine = _FX_ENGINE_GUARDED.replace(
        "    def process_epoch(self, state):\n",
        "    def process_epoch(self, state):\n"
        "        cs.save(state)\n")
    ckpt = ("class CheckpointStore:\n"
            "    def save(self, sim):\n"
            "        return 1\n")
    findings = effects_pass.check_tree(_fx_tree(
        tmp_path, engine=engine,
        extra=[("consensus_specs_tpu/recovery/checkpoint.py", ckpt)]))
    assert [f.code for f in findings] == ["E1203"]


# -- shard safety -----------------------------------------------------------

_FX_SHARD = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "from jax.experimental.shard_map import shard_map\n"
    "PSUM_BUDGET = {'demo': 1}\n"
    "def _p_sums(mesh):\n"
    "    def build():\n"
    "        def local(eff):\n"
    "            return jax.lax.psum(jnp.sum(eff), 'v')\n"
    "        return jax.jit(shard_map(local, mesh=mesh))\n"
    "    return build()\n"
    "def _dispatch(spec, state, sub, fast):\n"
    "    return fast(spec, state, None)\n"
    "def try_demo(spec, state):\n"
    "    def fast(spec, state, sa):\n"
    "        prog = _p_sums(state)\n"
    "        return True\n"
    "    return _dispatch(spec, state, 'demo', fast)\n")

_SHARD_REL = "consensus_specs_tpu/parallel/prog.py"


def _shard_findings(src):
    return fx.analyze_shard_module(_SHARD_REL, _e_ast.parse(src))


def test_e1214_budget_proven_on_fixture():
    findings, verdicts = _shard_findings(_FX_SHARD)
    assert findings == []
    assert any("[PROVEN]" in v and "demo" in v for v in verdicts)


def test_e1214_budget_mismatch_fires():
    src = _FX_SHARD.replace("PSUM_BUDGET = {'demo': 1}",
                            "PSUM_BUDGET = {'demo': 0}")
    findings, _ = _shard_findings(src)
    assert "E1214" in [f.code for f in findings]


def test_e1214_stacked_psum_discipline():
    src = _FX_SHARD.replace(
        "            return jax.lax.psum(jnp.sum(eff), 'v')\n",
        "            a = jax.lax.psum(jnp.sum(eff), 'v')\n"
        "            b = jax.lax.psum(jnp.max(eff), 'v')\n"
        "            return a + b\n")
    findings, _ = _shard_findings(src)
    codes = [f.code for f in findings]
    assert codes.count("E1214") >= 2     # >1 psum per program + != budget


def test_e1214_unbudgeted_sub_and_stale_entry():
    src = _FX_SHARD.replace("'demo', fast", "'other', fast")
    findings, _ = _shard_findings(src)
    msgs = " ".join(f.message for f in findings)
    assert "'other'" in msgs and "stale" in msgs


def test_e1211_captured_host_state_in_body():
    src = (
        "from jax.experimental.shard_map import shard_map\n"
        "def _p_bad(mesh, sa):\n"
        "    cols = sa.registry()\n"
        "    def build():\n"
        "        def local(eff):\n"
        "            return eff + cols['eff']\n"
        "        return shard_map(local, mesh=mesh)\n"
        "    return build()\n")
    findings, _ = _shard_findings(src)
    assert [f.code for f in findings] == ["E1211"]
    assert "cols" in findings[0].message


def test_e1211_static_config_capture_is_clean():
    src = (
        "from jax.experimental.shard_map import shard_map\n"
        "def _p_ok(mesh, static):\n"
        "    (increment, in_leak) = static\n"
        "    weights = (1, 2, 3)\n"
        "    def build():\n"
        "        import jax.numpy as jnp\n"
        "        def local(eff):\n"
        "            return eff * jnp.uint64(weights[0] + increment)\n"
        "        return shard_map(local, mesh=mesh)\n"
        "    return build()\n")
    findings, _ = _shard_findings(src)
    assert findings == []


def test_e1212_host_concretization_in_body():
    src = (
        "import numpy as np\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def _p_bad(mesh):\n"
        "    def build():\n"
        "        def local(eff):\n"
        "            n = int(eff.sum())\n"
        "            return np.asarray(eff) * n\n"
        "        return shard_map(local, mesh=mesh)\n"
        "    return build()\n")
    findings, _ = _shard_findings(src)
    assert [f.code for f in findings] == ["E1212", "E1212"]


def test_e1213_inplace_accessor_mutation():
    src = (
        "def bad(sa):\n"
        "    b = sa.balances()\n"
        "    b[0] = 1\n"
        "def bad_view(sa):\n"
        "    cols = sa.registry()\n"
        "    eff = cols['eff']\n"
        "    eff[2] += 1\n"
        "def good_copy(sa):\n"
        "    b = sa.balances().copy()\n"
        "    b[0] = 1\n"
        "def sanctioned(sa, new):\n"
        "    sa.registry_writable()['eff'] = new\n")
    findings = fx.check_placement_retirement(
        "consensus_specs_tpu/ops/consumer.py", _e_ast.parse(src))
    assert [f.code for f in findings] == ["E1213", "E1213"]
    assert findings[0].line == 3 and findings[1].line == 7


# -- write ordering ---------------------------------------------------------

_ORD_REL = "consensus_specs_tpu/recovery/writer.py"


def _ordering(src, fsync_scope=True):
    return fx.analyze_ordering(_ORD_REL, _e_ast.parse(src),
                               fsync_scope=fsync_scope)


def test_e1221_manifest_last_proven_and_violated():
    good = (
        "def write_gen(cs, gen):\n"
        "    atomic_write_bytes(cs.blob_path(gen, 'a'), b'')\n"
        "    atomic_write_bytes(cs.blob_path(gen, 'b'), b'')\n"
        "    atomic_write_json(cs.manifest_path(gen), {})\n")
    findings, verdicts = _ordering(good)
    assert findings == []
    assert any("manifest-written-last" in v for v in verdicts)
    bad = (
        "def write_gen(cs, gen):\n"
        "    atomic_write_json(cs.manifest_path(gen), {})\n"
        "    atomic_write_bytes(cs.blob_path(gen, 'a'), b'')\n")
    findings, _ = _ordering(bad)
    assert [f.code for f in findings] == ["E1221"]


def test_e1222_record_after_step_marker():
    bad = (
        "def drive(journal, step):\n"
        "    journal.commit_step(0, step)\n"
        "    journal.append(BLOCK, b'')\n")
    findings, _ = _ordering(bad)
    assert [f.code for f in findings] == ["E1222"]
    good = bad.replace(
        "    journal.commit_step(0, step)\n    journal.append(BLOCK, b'')\n",
        "    journal.append(BLOCK, b'')\n    journal.commit_step(0, step)\n")
    findings, verdicts = _ordering(good)
    assert findings == []
    assert any("precede their STEP commit marker" in v for v in verdicts)


def test_e1222_step_writer_must_fsync():
    bad = (
        "import os\n"
        "STEP = 5\n"
        "def frame(kind, payload):\n"
        "    return payload\n"
        "class J:\n"
        "    def commit_step(self, ordinal):\n"
        "        self._f.write(frame(STEP, b''))\n")
    findings, _ = _ordering(bad)
    assert [f.code for f in findings] == ["E1222"]
    good = bad.replace(
        "        self._f.write(frame(STEP, b''))\n",
        "        self._f.write(frame(STEP, b''))\n"
        "        os.fsync(self._f.fileno())\n")
    findings, verdicts = _ordering(good)
    assert findings == []
    assert any("STEP marker fsynced" in v for v in verdicts)


def test_e1223_rename_needs_preceding_fsync():
    bad = (
        "import os\n"
        "def torn(path, data):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'wb') as f:\n"
        "        f.write(data)\n"
        "    os.replace(tmp, path)\n")
    findings, _ = _ordering(bad)
    assert [f.code for f in findings] == ["E1223"]
    good = bad.replace(
        "    os.replace(tmp, path)\n",
        "    os.fsync(3)\n    os.replace(tmp, path)\n")
    findings, verdicts = _ordering(good)
    assert findings == []
    assert any("fsync-before-rename holds" in v for v in verdicts)
    # outside the durable scopes the rule does not apply (generator
    # outputs are fenced by the INCOMPLETE-tag protocol instead)
    findings, _ = _ordering(bad, fsync_scope=False)
    assert findings == []


# -- real-tree acceptance ---------------------------------------------------

def test_effects_real_tree_baseline_zero():
    """THE acceptance criterion: the repo proves every effect contract
    — commit-scope discipline, psum budget, write orderings — with
    nothing baselined (the one justified ``# noqa: E1223`` on
    ``atomic_replace_bytes`` is suppression-with-reason, not debt)."""
    findings = driver.run_passes(driver.Context(REPO),
                                 pass_names={"effects"})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_effects_real_tree_proofs_nonvacuous():
    ctx = driver.Context(REPO)
    lines = effects_pass.verdict_report(ctx)
    text = "\n".join(lines)
    assert "[FAIL]" not in text
    # the three headline proofs of the acceptance criteria
    assert "manifest-written-last" in text
    assert "rewards_and_penalties budget=1" in text
    assert "0 escape a scope unguarded" in text
    assert "STEP marker fsynced" in text
    # non-vacuity: the closure really carries deferrable write sites
    # and the scope roots really exist
    analysis = ctx._effects_scope_memo
    assert len(analysis.scopes) >= 2
    assert sum(len(ev.writes) for ev in analysis._events.values()) > 0
    # increase_balance's own summary is an unguarded write — only the
    # guarded call edges keep it out of the scopes
    inc = analysis.graph.classes["Phase0Spec"].methods["increase_balance"]
    assert any(f[0] == "uwrite" for f in analysis._summaries[inc])


def test_effect_verdicts_cli(capsys):
    assert driver.main([REPO, "--effect-verdicts"]) == 0
    out = capsys.readouterr().out
    assert "PSUM" in out.upper() or "psum" in out
    assert "[PROVEN]" in out


# -- dependency-granular cache + --changed + warm budget --------------------

def test_input_shas_for_scopes_tree_passes():
    from consensus_specs_tpu.tools.speclint.passes import (
        coverage as cov_pass, determinism as det_pass)
    ctx = driver.Context(REPO)
    eff_files = {r for r, _ in ctx.input_shas_for(effects_pass)}
    cov_files = {r for r, _ in ctx.input_shas_for(cov_pass)}
    det_files = {r for r, _ in ctx.input_shas_for(det_pass)}
    assert not any(r.startswith("tests/") for r in eff_files)
    assert not any(r.startswith("consensus_specs_tpu/tools/")
                   for r in eff_files | det_files | cov_files)
    assert any(r.startswith("tests/") for r in cov_files)
    assert "Makefile" in cov_files
    # passes without the declaration keep the whole tree
    class _Plain:
        pass
    assert {r for r, _ in ctx.input_shas_for(_Plain)} \
        == {r for r, _ in ctx.input_shas()}


def test_tree_cache_dependency_granularity(tmp_path):
    """Editing a tests/ file re-runs ONLY the coverage pass; the other
    tree passes (ladder, determinism, effects) stay warm."""
    root = tmp_path / "repo"
    _write(root, SCOPED, "def f(seq):\n    return u64_column(seq)\n")
    _write(root, "tests/test_probe.py", "def test_ok():\n    pass\n")
    assert driver.main([str(root)]) == 0
    _write(root, "tests/test_probe.py", "def test_ok():\n    assert 1\n")
    ctx = driver.Context(str(root))
    cache = sl_cache.AnalysisCache(
        os.path.join(str(root), sl_cache.CACHE_NAME), driver._pass_salt())
    driver.run_passes(ctx, cache=cache)
    assert cache.stats["tree_misses"] == 1     # coverage only
    assert cache.stats["tree_hits"] == 4       # ladder/determinism/effects/cost


def test_warm_lint_time_budget(tmp_path):
    """The satellite bound: a warm full lint of the REAL tree serves
    everything from the cache inside the asserted budget."""
    cache_path = str(tmp_path / "cache.json")
    ctx = driver.Context(REPO)
    cache = sl_cache.AnalysisCache(cache_path, driver._pass_salt())
    driver.run_passes(ctx, cache=cache)
    cache.save()
    ctx2 = driver.Context(REPO)
    cache2 = sl_cache.AnalysisCache(cache_path, driver._pass_salt())
    t0 = time.perf_counter()
    driver.run_passes(ctx2, cache=cache2)
    took = time.perf_counter() - t0
    assert cache2.stats["file_misses"] == 0
    assert cache2.stats["tree_misses"] == 0
    assert took < 5.0, f"warm lint took {took:.2f}s (budget 5s)"


def test_changed_mode_lints_only_dirty(tmp_path, capsys):
    if shutil.which("git") is None:
        import pytest
        pytest.skip("git unavailable")
    root = tmp_path / "repo"
    dirty_src = ("def f(seq):\n"
                 "    b = u64_column(seq)\n"
                 "    p = u64_column(seq)\n"
                 "    return b - p\n")
    _write(root, SCOPED, "def f(seq):\n    return u64_column(seq)\n")
    _write(root, "consensus_specs_tpu/utils/other.py", dirty_src)
    for cmd in (["git", "init", "-q"],
                ["git", "config", "user.email", "t@t"],
                ["git", "config", "user.name", "t"],
                ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=str(root), check=True)
    # dirty exactly one file with a fresh finding
    _write(root, SCOPED, dirty_src)
    rc = driver.main([str(root), "--changed", "--no-baseline",
                      "--no-incremental"])
    out = capsys.readouterr().out
    assert rc == 1
    assert SCOPED in out
    # the committed-but-unchanged file's identical finding is NOT
    # reported: --changed restricted the file-pass candidates
    assert "consensus_specs_tpu/utils/other.py" not in out


def test_durability_covers_compiler_tree():
    """The real E12xx-era finding: the spec compiler's module/manifest
    writes were bare final-path opens — R901's scope now guards the
    compiler tree so the torn-write idiom cannot come back."""
    assert durability.in_scope("consensus_specs_tpu/compiler/emit.py")
    src = ("def emit(path, src):\n"
           "    with open(path, 'w') as f:\n"
           "        f.write(src)\n")
    findings = durability.check_source(
        "consensus_specs_tpu/compiler/emit.py", src)
    assert [f.code for f in findings] == ["R901"]


# ---------------------------------------------------------------------------
# review regressions (E12xx)
# ---------------------------------------------------------------------------

def test_e1202_finding_anchors_in_defining_file(tmp_path):
    """Review regression: a fork/checkpoint fact escaping to a scope in
    ANOTHER file must anchor at its own call site, not at an arbitrary
    line of the scope root's file (noqa matching is path+line)."""
    engine = _FX_ENGINE_GUARDED.replace(
        "from consensus_specs_tpu.state import arrays as state_arrays\n",
        "from consensus_specs_tpu.state import arrays as state_arrays\n"
        "from consensus_specs_tpu.ops.helper import deep_fork\n").replace(
        "    def process_epoch(self, state):\n",
        "    def process_epoch(self, state):\n"
        "        deep_fork(state)\n")
    helper = (
        "from consensus_specs_tpu.state import arrays as state_arrays\n"
        "def deep_fork(state):\n"
        "    return state_arrays.fork_state(state)\n")
    findings = effects_pass.check_tree(_fx_tree(
        tmp_path, engine=engine,
        extra=[("consensus_specs_tpu/ops/helper.py", helper)]))
    assert [f.code for f in findings] == ["E1202"]
    assert findings[0].path == "consensus_specs_tpu/ops/helper.py"
    assert findings[0].line == 3


def test_changed_mode_sees_untracked_directories(tmp_path, capsys):
    """Review regression: `git status --porcelain` collapses a new
    directory to one `?? dir/` entry; --changed must still lint the
    files inside it (--untracked-files=all)."""
    if shutil.which("git") is None:
        import pytest
        pytest.skip("git unavailable")
    root = tmp_path / "repo"
    _write(root, "consensus_specs_tpu/utils/seed.py", "x = 1\n")
    for cmd in (["git", "init", "-q"],
                ["git", "config", "user.email", "t@t"],
                ["git", "config", "user.name", "t"],
                ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=str(root), check=True)
    # a brand-new untracked DIRECTORY containing a finding (under a
    # uint64-pass-scoped prefix)
    _write(root, "consensus_specs_tpu/parallel/newpkg/kernels.py",
           "def f(seq):\n"
           "    b = u64_column(seq)\n"
           "    p = u64_column(seq)\n"
           "    return b - p\n")
    rc = driver.main([str(root), "--changed", "--no-baseline",
                      "--no-incremental"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "consensus_specs_tpu/parallel/newpkg/kernels.py" in out


# ---------------------------------------------------------------------------
# N13xx cost pass: asymptotic host-work proofs over the registry axis
# ---------------------------------------------------------------------------

from consensus_specs_tpu.tools.speclint.passes import cost as cost_pass

_CX_ON_BODY = (
    "        cols = sa.registry()\n"
    "        eff = cols['eff']\n"
    "        total = eff.sum()\n"
    "        return True\n")

_CX_OS_BODY = (
    "        parts = np.asarray(_p_stats(None)(sa.registry()))\n"
    "        total = parts.max()\n"
    "        return True\n")

_CX_ENGINE = (
    "import numpy as np\n"
    "def _dispatch(spec, state, sub, fast):\n"
    "    return fast(spec, state, None)\n"
    "def _p_stats(mesh):\n"
    "    def build():\n"
    "        def local(eff):\n"
    "            return eff\n"
    "        return local\n"
    "    return build()\n"
    "def try_demo(spec, state):\n"
    "    def fast(spec, state, sa):\n"
    "{body}"
    "    return _dispatch(spec, state, 'demo', fast)\n")

_CX_REL = "consensus_specs_tpu/parallel/demo_engine.py"


def _cx_tree(tmp_path, body=_CX_ON_BODY, prefix="", rel=_CX_REL):
    root = tmp_path / "repo"
    _write(root, rel, prefix + _CX_ENGINE.format(body=body))
    return str(root)


def test_n1301_column_reduce_in_dispatch_path(tmp_path):
    findings = cost_pass.check_tree(_cx_tree(tmp_path))
    assert "N1301" in _codes(findings)
    (f,) = [f for f in findings if f.code == "N1301"]
    assert f.path == _CX_REL
    verdicts = cost_pass.analysis_for(_cx_tree(tmp_path)).verdicts()
    assert any("[FAIL]" in v and "demo" in v for v in verdicts)


def test_n1301_partial_reduce_is_proven(tmp_path):
    root = _cx_tree(tmp_path, body=_CX_OS_BODY)
    assert _codes(cost_pass.check_tree(root)) == []
    verdicts = cost_pass.analysis_for(root).verdicts()
    assert any("[PROVEN]" in v and "O(S)" in v for v in verdicts)


def test_n1301_noqa_suppresses_and_counts(tmp_path):
    body = _CX_ON_BODY.replace("eff.sum()", "eff.sum()  # noqa: N1301")
    root = _cx_tree(tmp_path, body=body)
    assert _codes(cost_pass.check_tree(root)) == []
    verdicts = cost_pass.analysis_for(root).verdicts()
    assert any("[PROVEN]" in v and "suppressed" in v for v in verdicts)


def test_n1301_interprocedural_through_function_arg(tmp_path):
    # the _supervised(..., fast_fn) convention: the O(n) body is only
    # reachable through a function REFERENCE passed as an argument
    body = "        return _run(spec, state, _worker)\n"
    prefix = (
        "def _worker(spec, state, sa):\n"
        "    eff = u64_column(state)\n"
        "    return int(eff.sum())\n"
        "def _run(spec, state, fn):\n"
        "    return fn(spec, state, None)\n")
    findings = cost_pass.check_tree(
        _cx_tree(tmp_path, body=body, prefix=prefix))
    assert "N1301" in _codes(findings)
    (f,) = [f for f in findings if f.code == "N1301"]
    assert "_worker" in f.message


def test_n1301_audit_branch_is_exempt(tmp_path):
    body = (
        "        if supervisor.audit_due('demo'):\n"
        "            g = sa.registry()['eff'].sum()\n"
        "        return True\n")
    assert _codes(cost_pass.check_tree(_cx_tree(tmp_path, body=body))) \
        == []


def test_n1302_gather_only_column_derivation(tmp_path):
    body = (
        "        eff = sa.registry()['eff']\n"
        "        base = eff * np.uint64(64)\n"
        "        src_idx = np.nonzero(state.flags)[0]\n"
        "        out = base[src_idx]\n"
        "        return True\n")
    codes = _codes(cost_pass.check_tree(_cx_tree(tmp_path, body=body)))
    assert "N1302" in codes


def test_n1303_unbounded_cache_and_bounded_annotation(tmp_path):
    body = (
        "        _CACHE[(id(spec), id(state))] = 1\n"
        "        return True\n")
    prefix = "_CACHE = {}\n"
    codes = _codes(cost_pass.check_tree(
        _cx_tree(tmp_path, body=body, prefix=prefix)))
    assert "N1303" in codes
    bounded = "# speclint: cost: bounded: one probe pair\n" + prefix
    assert "N1303" not in _codes(cost_pass.check_tree(
        _cx_tree(tmp_path, body=body, prefix=bounded)))


def test_n1303_evicted_cache_is_clean(tmp_path):
    body = (
        "        _CACHE.pop(None, None)\n"
        "        _CACHE[(id(spec), id(state))] = 1\n"
        "        return True\n")
    assert "N1303" not in _codes(cost_pass.check_tree(
        _cx_tree(tmp_path, body=body, prefix="_CACHE = {}\n")))


def test_n1304_checked_annotations(tmp_path):
    # an O(1) claim on an O(n) path fails; an honest O(n) claim and a
    # matching O(S) claim both verify; a malformed bound is reported
    over = _cx_tree(tmp_path, prefix="")
    src = open(os.path.join(over, _CX_REL)).read()
    with open(os.path.join(over, _CX_REL), "w") as f:
        f.write(src.replace("def try_demo(spec, state):\n",
                            "# speclint: cost: O(1)\n"
                            "def try_demo(spec, state):\n"))
    findings = cost_pass.check_tree(over)
    assert any(f.code == "N1304" and "O(n)" in f.message
               for f in findings)
    with open(os.path.join(over, _CX_REL), "w") as f:
        f.write(src.replace("def try_demo(spec, state):\n",
                            "# speclint: cost: O(n)\n"
                            "def try_demo(spec, state):\n"))
    assert "N1304" not in _codes(cost_pass.check_tree(over))
    with open(os.path.join(over, _CX_REL), "w") as f:
        f.write(src.replace("def try_demo(spec, state):\n",
                            "# speclint: cost: O(n^2)\n"
                            "def try_demo(spec, state):\n"))
    assert any(f.code == "N1304" and "unparseable" in f.message
               for f in findings + cost_pass.check_tree(over))


def test_cost_real_tree_baseline_zero():
    """Acceptance: the REAL tree carries zero unsuppressed N13xx debt
    (the baseline records none), and every dispatch path proves O(S)."""
    assert cost_pass.check_tree(REPO) == []
    verdicts = cost_pass.analysis_for(REPO).verdicts()
    assert len(verdicts) >= 5
    assert all("[PROVEN]" in v for v in verdicts)
    assert not any("[FAIL]" in v for v in verdicts)


def test_cost_real_tree_proofs_nonvacuous():
    """The proofs must be doing work on the real tree: the shard
    programs pin at O(n/S), and at least one dispatch path reduces a
    per-shard partial stack (an O(S) fact on parallel/)."""
    from consensus_specs_tpu.tools.speclint import cost as cost_core
    a = cost_pass.analysis_for(REPO)
    assert any(total == cost_core.ONS
               for total, _ in a.summaries.values())
    os_facts = 0
    for fn in a.reachable():
        if fn in a._pinned or not fn.rel.startswith(
                "consensus_specs_tpu/parallel/"):
            continue
        for _, rank, reportable, _ in a._local(fn).facts:
            if reportable and rank == cost_core.OS:
                os_facts += 1
    assert os_facts >= 1


def test_cost_verdicts_cli(capsys):
    assert driver.main([REPO, "--cost-verdicts"]) == 0
    out = capsys.readouterr().out
    assert "host-work budget" in out
    assert "[PROVEN]" in out and "[FAIL]" not in out


# ---------------------------------------------------------------------------
# SARIF baselineState: "absent" (fixed baseline debt)
# ---------------------------------------------------------------------------

def test_sarif_absent_for_stale_baseline_keys():
    log = sarif.to_sarif([], [], stale=["consensus_specs_tpu/x.py::U101"])
    assert sarif.validate(log) == []
    (result,) = log["runs"][0]["results"]
    assert result["baselineState"] == "absent"
    assert result["ruleId"] == "U101"
    assert result["level"] == "none"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "consensus_specs_tpu/x.py"
    assert loc["region"]["startLine"] == 1
    rule_ids = {r["id"] for r in log["runs"][0]["tool"]["driver"]["rules"]}
    assert "U101" in rule_ids


def test_sarif_driver_emits_absent_for_fixed_debt(tmp_path, capsys):
    """End-to-end: a baseline entry whose finding is gone surfaces as a
    schema-valid `absent` result in `--format sarif`."""
    root = tmp_path / "repo"
    _write(root, SCOPED, "def f(seq):\n    return u64_column(seq)\n")
    _write(root, driver.BASELINE_NAME, json.dumps(
        {"counts": {SCOPED + "::U101": 1}}))
    rc = driver.main([str(root), "--passes", "uint64",
                      "--format", "sarif"])
    assert rc == 0
    log = json.loads(capsys.readouterr().out)
    assert sarif.validate(log) == []
    states = [r["baselineState"] for r in log["runs"][0]["results"]]
    assert states == ["absent"]


# ---------------------------------------------------------------------------
# --changed vs renamed / deleted dirty files
# ---------------------------------------------------------------------------

def test_changed_mode_purges_renamed_and_deleted(tmp_path, capsys):
    """Review regression: a dirty rename (R old -> new) or delete (D)
    must purge the OLD path's cached findings — a stale cache entry
    would otherwise resurrect findings for a file that no longer
    exists."""
    if shutil.which("git") is None:
        import pytest
        pytest.skip("git unavailable")
    root = tmp_path / "repo"
    buggy = ("def f(seq):\n"
             "    b = u64_column(seq)\n"
             "    p = u64_column(seq)\n"
             "    return b - p\n")
    old_rel = "consensus_specs_tpu/parallel/old_kernels.py"
    dead_rel = "consensus_specs_tpu/parallel/dead_kernels.py"
    new_rel = "consensus_specs_tpu/parallel/new_kernels.py"
    _write(root, old_rel, buggy)
    _write(root, dead_rel, buggy)
    for cmd in (["git", "init", "-q"],
                ["git", "config", "user.email", "t@t"],
                ["git", "config", "user.name", "t"],
                ["git", "add", "-A"],
                ["git", "commit", "-qm", "seed"]):
        subprocess.run(cmd, cwd=str(root), check=True)
    # warm the cache with both files' findings
    assert driver.main([str(root), "--no-baseline"]) == 1
    capsys.readouterr()
    cache_path = os.path.join(str(root), sl_cache.CACHE_NAME)
    cache = sl_cache.AnalysisCache(cache_path, driver._pass_salt())
    assert old_rel in cache._data["files"]
    assert dead_rel in cache._data["files"]
    # dirty: rename one file (staged, R entry), delete the other
    subprocess.run(["git", "mv", old_rel, new_rel], cwd=str(root),
                   check=True)
    os.remove(os.path.join(str(root), dead_rel))
    rc = driver.main([str(root), "--changed", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert new_rel in out
    assert old_rel not in out and dead_rel not in out
    cache = sl_cache.AnalysisCache(cache_path, driver._pass_salt())
    assert old_rel not in cache._data["files"]
    assert dead_rel not in cache._data["files"]
    assert new_rel in cache._data["files"]
