"""Differential + unit suite for the mesh-sharded SPMD state engine
(``consensus_specs_tpu/parallel/mesh_state.py`` / ``mesh_epoch.py`` /
``mesh_merkle.py``).

The conftest pins an 8-device virtual CPU mesh before the first jax
import, so every test here exercises REAL SPMD partitioning —
``shard_map`` programs, ``NamedSharding`` placements, ``psum``
collectives — without TPU hardware (the CI ``mesh`` job runs this file
under the same ``XLA_FLAGS`` leg explicitly, plus the ``CS_TPU_MESH=0``
off-leg).

Contracts:

* **byte-identity** — epoch transitions and state roots identical
  across {mesh on, mesh off, spec loop} on the 12-fork differential
  states, with the engine-commit counters asserted so a silent decline
  cannot turn the comparison into a tautology;
* **collective budget** — every reduction program carries exactly ONE
  psum, every elementwise program ZERO, proven structurally on the
  jaxprs;
* **placement lifecycle** — device placements cache on the store cells,
  ride copy-on-write forks for free, and retire on column writes;
  16 mesh-forked replays stay byte-identical to independent
  store-off/mesh-off replays; a ``fork_state`` inside an open
  ``commit_scope`` strands nothing;
* **harness contract** — the ``mesh.epoch`` / ``mesh.merkle`` sites
  take injected faults as counted reason-labeled fallbacks
  (byte-identical degradation) and rate-1 sentinel audits catch a
  corrupt-mode result with a quarantine.
"""
from random import Random

import numpy as np
import pytest

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.ops import epoch_kernels as ek
from consensus_specs_tpu.parallel import mesh_epoch, mesh_merkle, mesh_state
from consensus_specs_tpu.state import arrays
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.test_infra.genesis import create_genesis_state
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import (
    List, hash_tree_root, uint64)

from tests.test_epoch_vectorized import (
    ALTAIR_FAMILY, PHASE0_FAMILY, _altair_state, _phase0_state)

N_VALIDATORS = 64


@pytest.fixture(autouse=True)
def _mode_reset():
    prev_bls = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev_bls
    ek.use_auto()
    arrays.use_auto()
    mesh_state.use_auto()
    mesh_state.restore_devices()


def _require_mesh():
    if mesh_state.device_count() < 2:
        pytest.skip("needs a multi-device host (conftest forces 8 "
                    "virtual CPU devices)")


def _genesis(spec):
    return create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * N_VALIDATORS,
        spec.MAX_EFFECTIVE_BALANCE)


# ---------------------------------------------------------------------------
# mesh construction / switch plumbing
# ---------------------------------------------------------------------------

def test_build_mesh_derived_and_memoized():
    _require_mesh()
    import jax
    mesh = mesh_state.build_mesh()
    assert mesh is mesh_state.build_mesh()          # memoized identity
    assert mesh.shape[mesh_state.AXIS] == len(jax.devices())
    pts = mesh_state.build_mesh("points")
    assert pts.axis_names == ("points",)
    assert pts is mesh_state.build_mesh("points")


def test_pad_amount_uneven_shards():
    assert mesh_state.pad_amount(16, 8) == 0
    assert mesh_state.pad_amount(17, 8) == 7
    assert mesh_state.pad_amount(5, 8) == 3
    assert mesh_state.pad_amount(0, 8) == 0
    # a non-power-of-two device count shards too
    assert mesh_state.pad_amount(16, 6) == 2


def test_env_flag_disables_auto(monkeypatch):
    _require_mesh()
    monkeypatch.setenv("CS_TPU_MESH", "0")
    mesh_state.use_auto()
    assert not mesh_state.enabled()
    assert mesh_state.backend_name() == "fallback"
    # live re-read: flipping the variable after import works
    monkeypatch.setenv("CS_TPU_MESH", "1")
    assert mesh_state.enabled()
    assert mesh_state.backend_name() == "mesh"
    # unset restores the import-time default, whatever it was
    monkeypatch.delenv("CS_TPU_MESH")
    from consensus_specs_tpu.utils import env_flags
    assert mesh_state.enabled() == \
        env_flags._SWITCH_DEFAULTS["CS_TPU_MESH"]


def test_engagement_floor(monkeypatch):
    _require_mesh()
    monkeypatch.setenv("CS_TPU_MESH", "1")
    mesh_state.use_auto()
    monkeypatch.setenv("CS_TPU_MESH_MIN", "1000")
    assert not mesh_state.engaged(999)
    assert mesh_state.engaged(1000)
    # forcing the engine bypasses the floor (but not the device gate)
    mesh_state.use_mesh()
    assert mesh_state.engaged(mesh_state.device_count())


# ---------------------------------------------------------------------------
# collective budget (structural)
# ---------------------------------------------------------------------------

def test_psum_census_matches_budget():
    """Every reduction program: exactly ONE psum; every elementwise
    program: ZERO — the structural half of the bench smoke's counter
    assertion (``mesh_epoch.PSUM_BUDGET``)."""
    _require_mesh()
    import jax
    mesh = mesh_state.build_mesh()
    n = 4 * mesh_state.device_count()
    u64 = np.zeros(n, dtype=np.uint64)
    u8 = np.zeros(n, dtype=np.uint8)
    bl = np.zeros(n, dtype=bool)
    scal = np.zeros(8, dtype=np.uint64)

    def psums(prog, *args):
        with mesh_state.x64():
            return str(jax.make_jaxpr(prog)(*args)).count("psum")

    assert psums(mesh_epoch._p_altair_sums(mesh, 3),
                 u64, u64, u64, bl, u8, scal) == 1
    assert psums(mesh_epoch._p_masked_sums(mesh),
                 u64, np.zeros((4, n), dtype=bool)) == 1
    assert psums(mesh_epoch._p_active_sums(mesh, 3),
                 u64, u64, u64, np.zeros((3, n), dtype=bool), scal) == 1
    assert psums(mesh_epoch._p_active_sums(mesh, 0),
                 u64, u64, u64, scal) == 1
    assert psums(mesh_epoch._p_registry_scan(
        mesh, (2**64 - 1, 32, 16, 256)), u64, u64, u64, u64, scal) == 1
    # the per-shard stat stacks (exact guard maxima) are pure partials:
    # the host reduces over S elements, the device never communicates
    assert psums(mesh_epoch._p_shard_stats(mesh, 2), u64, u64) == 0
    assert psums(mesh_epoch._p_altair_deltas(
        mesh, (False, (14, 26, 14), 64, 10**9, 2, 1)),
        u64, u64, u64, bl, u64, u8, u64, u64, scal) == 0
    assert psums(mesh_epoch._p_inactivity(mesh, (4, 16, False, 1)),
                 u64, u64, bl, u64, u8, u64, scal) == 0
    assert psums(mesh_epoch._p_slashings(mesh, (10**9,)),
                 u64, bl, u64, u64, scal) == 0
    assert psums(mesh_epoch._p_eff_balance(
        mesh, (10**9, 10**8, 10**8, 32 * 10**9)), u64, u64) == 0
    # the inclusion-delay scatter-min scan is shard-local by
    # construction: every validator lane lives on exactly one shard,
    # so the rewards budget stays at ONE psum with the scan added
    assert psums(mesh_epoch._p_incl_scan(mesh), u64,
                 np.zeros(16, dtype=np.int64),
                 np.zeros(16, dtype=np.uint64)) == 0


# ---------------------------------------------------------------------------
# epoch differential: mesh vs single-device vs spec loop
# ---------------------------------------------------------------------------

def _epoch_differential(spec, state):
    s_loop, s_single, s_mesh = state.copy(), state.copy(), state.copy()
    ek.use_loops()
    mesh_state.use_fallback()
    spec.process_epoch(s_loop)
    ek.use_vectorized()
    spec.process_epoch(s_single)
    mesh_state.use_mesh()
    arrays.use_arrays()
    with counting() as delta:
        spec.process_epoch(s_mesh)
    assert delta["mesh.epoch{path=mesh}"] > 0, \
        f"{spec.fork}: mesh engine never committed"
    assert delta["mesh.epoch.fallbacks{reason=guard}"] == 0, \
        f"{spec.fork}: unexpected mesh guard fallback"
    r = bytes(hash_tree_root(s_loop))
    assert bytes(hash_tree_root(s_single)) == r, \
        f"{spec.fork}: single-device root diverged from the spec loop"
    assert bytes(hash_tree_root(s_mesh)) == r, \
        f"{spec.fork}: mesh root diverged"
    return delta


@pytest.mark.parametrize("fork", ALTAIR_FAMILY)
def test_altair_family_mesh_differential(fork):
    _require_mesh()
    spec, state = _altair_state(fork)
    delta = _epoch_differential(spec, state)
    # all five sub-transitions through the SPMD programs, on budget
    assert delta["mesh.epoch{path=mesh}"] == 5
    for sub, budget in mesh_epoch.PSUM_BUDGET.items():
        assert delta[f"mesh.psums{{site={sub}}}"] == budget, sub


@pytest.mark.parametrize("fork", PHASE0_FAMILY)
def test_phase0_family_mesh_differential(fork):
    _require_mesh()
    spec, state = _phase0_state(fork)
    delta = _epoch_differential(spec, state)
    assert delta["mesh.epoch{path=mesh}"] == 4   # no inactivity scores


def test_leak_epoch_mesh_differential():
    _require_mesh()
    spec, state = _altair_state("altair", leak=True, seed=23)
    _epoch_differential(spec, state)


def test_guard_fallback_counted_and_identical():
    """A uint64-overflow-risk state declines the mesh (counted
    reason=guard), falls to the single-device engine — which re-checks
    its own exact guards — and the result stays byte-identical."""
    _require_mesh()
    spec, state = _altair_state("altair", seed=29)
    state.inactivity_scores[3] = 10**9     # eff * score overflows a lane
    s_loop, s_mesh = state.copy(), state.copy()
    ek.use_loops()
    spec.process_rewards_and_penalties(s_loop)
    ek.use_vectorized()
    mesh_state.use_mesh()
    with counting() as delta:
        spec.process_rewards_and_penalties(s_mesh)
    assert delta["mesh.epoch.fallbacks{reason=guard}"] == 1
    assert hash_tree_root(s_loop) == hash_tree_root(s_mesh)


def test_scan_overflow_declines_counted_and_identical(monkeypatch):
    """A registry-eligibility family outgrowing the bounded per-shard
    index buffers declines the mesh dispatch (counted
    mesh.scan_overflow — a degradation-ladder leg, never a truncation)
    and the columnar engine serves the call byte-identically."""
    _require_mesh()
    spec, state = _altair_state("altair", seed=37)
    far = spec.FAR_FUTURE_EPOCH
    for i in range(4):                     # guaranteed queue candidates
        v = state.validators[i]
        v.activation_eligibility_epoch = far
        v.activation_epoch = far
        v.exit_epoch = far
    s_loop, s_mesh = state.copy(), state.copy()
    ek.use_loops()
    spec.process_registry_updates(s_loop)
    ek.use_vectorized()
    mesh_state.use_mesh()
    monkeypatch.setattr(mesh_epoch, "_SCAN_CAP", 1)
    with counting() as delta:
        spec.process_registry_updates(s_mesh)
    assert delta["mesh.scan_overflow"] == 1
    assert delta["mesh.epoch{path=mesh}"] == 0
    assert hash_tree_root(s_loop) == hash_tree_root(s_mesh)


def test_injected_fault_counted_and_identical():
    """An injected fault at mesh.epoch discharges exactly, books the
    reason=injected series (organic twin untouched), and the replay
    stays byte-identical — the PR-8 counted-fallback contract."""
    _require_mesh()
    spec, state = _altair_state("altair", seed=31)
    s_ref, s_inj = state.copy(), state.copy()
    ek.use_vectorized()
    mesh_state.use_mesh()
    arrays.use_arrays()
    spec.process_epoch(s_ref)
    sched = faults.FaultSchedule(triggers={"mesh.epoch": {1}})
    with counting() as delta:
        with faults.injected(sched):
            spec.process_epoch(s_inj)
    assert sched.fully_fired()
    assert delta["mesh.epoch.fallbacks{reason=injected}"] == 1
    assert delta["mesh.epoch.fallbacks{reason=guard}"] == 0
    assert hash_tree_root(s_ref) == hash_tree_root(s_inj)


def test_audit_catches_corrupt_epoch_result(monkeypatch, tmp_path):
    """Corrupt-mode mesh result + rate-1 sentinel audit: the host
    recomputation is authoritative (the wrong column never commits),
    the site quarantines, and the post-state is still byte-identical."""
    _require_mesh()
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))
    supervisor.reset()
    spec, state = _altair_state("altair", seed=37)
    s_ref, s_cor = state.copy(), state.copy()
    ek.use_vectorized()
    mesh_state.use_mesh()
    arrays.use_arrays()
    spec.process_epoch(s_ref)
    supervisor.reset()
    sched = faults.FaultSchedule(corrupt={"mesh.epoch": [1]})
    with counting() as delta:
        with faults.injected(sched):
            spec.process_epoch(s_cor)
    assert sched.corrupted, "corrupt mode never armed"
    assert delta["supervisor.quarantines{site=mesh.epoch}"] == 1
    assert supervisor.states()["mesh.epoch"] == "quarantined"
    assert hash_tree_root(s_ref) == hash_tree_root(s_cor)


# ---------------------------------------------------------------------------
# placement lifecycle over copy-on-write forks
# ---------------------------------------------------------------------------

def test_placement_cached_and_shared_across_forks():
    _require_mesh()
    spec = build_spec("altair", "minimal")
    state = _genesis(spec)
    arrays.use_arrays()
    mesh_state.use_mesh()
    mesh = mesh_state.build_mesh()
    sa = arrays.of(state)
    with counting() as delta:
        a = mesh_state.sharded_cell(sa, "balances", mesh)
        b = mesh_state.sharded_cell(sa, "balances", mesh)
    assert a is b
    assert delta["mesh.placements{column=balances}"] == 1
    # a copy-on-write fork shares the placement: no new transfer
    forked = arrays.fork_state(state)
    with counting() as delta:
        c = mesh_state.sharded_cell(arrays.of(forked), "balances", mesh)
    assert c is a
    assert delta["mesh.placements{column=balances}"] == 0
    # a column write retires it (identity key) — next read re-places
    sa.set_balances(sa.balances() + np.uint64(1))
    with counting() as delta:
        d = mesh_state.sharded_cell(sa, "balances", mesh)
    assert d is not a
    assert delta["mesh.placements{column=balances}"] == 1
    # ...while the fork still reads the OLD shared placement
    assert mesh_state.sharded_cell(arrays.of(forked), "balances",
                                   mesh) is a


def test_fork_during_commit_scope_no_stranded_pending():
    """Regression (satellite): a ``fork_state`` inside an open
    ``commit_scope`` with device-placed pending columns must commit
    the pending write into the child (fork commits first), share the
    post-commit placement, and leave the parent scope functional."""
    _require_mesh()
    spec = build_spec("altair", "minimal")
    state = _genesis(spec)
    arrays.use_arrays()
    mesh_state.use_mesh()
    mesh = mesh_state.build_mesh()
    sa = arrays.of(state)
    base = int(spec.MAX_EFFECTIVE_BALANCE)
    with arrays.commit_scope(state):
        sa.set_balances(sa.balances() + np.uint64(7))
        # place the PENDING column on the mesh (an engine read
        # mid-scope does exactly this)
        pending_placed = mesh_state.sharded_cell(sa, "balances", mesh)
        forked = arrays.fork_state(state)
        # fork committed the pending write first: child SSZ sees it
        assert int(forked.balances[0]) == base + 7
        # and the child's cell shares the (still-valid) placement —
        # nothing re-transferred, nothing stranded on the device
        with counting() as delta:
            child_placed = mesh_state.sharded_cell(
                arrays.of(forked), "balances", mesh)
        assert child_placed is pending_placed
        assert delta["mesh.placements{column=balances}"] == 0
        # parent scope still works after the mid-scope commit
        sa.set_balances(sa.balances() + np.uint64(5))
    assert int(state.balances[0]) == base + 12
    assert int(forked.balances[0]) == base + 7
    assert bytes(hash_tree_root(forked)) != bytes(hash_tree_root(state))


def test_sixteen_mesh_forked_replays_byte_identical():
    """Satellite: 16 replays forked from one base with the mesh engine
    ON (sharded columns, shared placements) must merkleize
    byte-identical to independent store-off mesh-off replays."""
    _require_mesh()
    spec, state = _altair_state("altair", seed=41)
    ek.use_vectorized()
    arrays.use_arrays()
    mesh_state.use_mesh()
    arrays.registry_of(state)
    arrays.of(state).balances()
    # warm the BASE placement: forks share it (fork() copies the cell's
    # shard alongside the data), so replay reads pay zero transfers
    # until their own copy-on-write registry write
    mesh_state.sharded_cell(arrays.of(state), "registry",
                            mesh_state.build_mesh())
    base_root = bytes(hash_tree_root(state))
    rng = Random(17)
    perturbs = [(rng.randrange(N_VALIDATORS),
                 int(spec.MAX_EFFECTIVE_BALANCE) // 2 + rng.randrange(100))
                for _ in range(16)]

    def replay(st, i, amount):
        st.balances[i] = amount
        next_epoch(spec, st)
        return bytes(hash_tree_root(st))

    with counting() as delta:
        forked_roots = [replay(arrays.fork_state(state), i, amt)
                        for i, amt in perturbs]
    assert delta["mesh.epoch{path=mesh}"] > 0
    assert delta["state_arrays.forks"] == 16
    # shared base placement: each replay re-places the registry at most
    # once (after its own copy-on-write registry write) instead of the
    # two transfers an unshared fork pays (initial read + post-write)
    assert delta["mesh.placements{column=registry}"] <= 16

    mesh_state.use_fallback()
    arrays.use_fallback()
    independent_roots = [replay(state.copy(), i, amt)
                         for i, amt in perturbs]
    assert forked_roots == independent_roots
    assert bytes(hash_tree_root(state)) == base_root


# ---------------------------------------------------------------------------
# leaf-span merkleization
# ---------------------------------------------------------------------------

def test_merkle_levels_byte_identical_fuzz():
    _require_mesh()
    mesh_state.use_mesh()
    rng = np.random.RandomState(3)
    for count, depth in [(16, 5), (17, 6), (63, 6), (100, 8),
                         (256, 40), (1000, 12)]:
        data = rng.bytes(count * 32)
        got = mesh_merkle.build_levels(data, depth)
        assert got is not None, (count, depth)
        golden = mesh_merkle._sequential_levels(data, depth)
        assert [bytes(a) for a in got] == [bytes(b) for b in golden], \
            (count, depth)


def test_merkle_wired_under_column_commit():
    """A registry-wide uint64 column commit (``set_leaves`` under the
    forest flush) routes its full tree rebuild through the leaf-span
    program — and the committed root matches per-index writes."""
    _require_mesh()
    BalanceList = List[uint64, 1 << 40]
    rng = Random(43)
    n = 512
    base = [rng.randrange(0, 2**40) for _ in range(n)]
    new = [v + 1 for v in base]
    ref = BalanceList(base)
    for i, v in enumerate(new):
        ref[i] = uint64(v)
    mesh_state.use_mesh()
    seq = BalanceList(base)
    hash_tree_root(seq)                  # warm the incremental tree
    with counting() as delta:
        ek._write_u64_list(seq, uint64,
                           np.array(base, dtype=np.uint64),
                           np.array(new, dtype=np.uint64))
        root = hash_tree_root(seq)
    assert delta["mesh.merkle{path=mesh}"] >= 1, \
        "chunk-packed commit never engaged the leaf-span program"
    assert bytes(root) == bytes(hash_tree_root(ref))


def test_merkle_injected_fault_counted_and_identical():
    _require_mesh()
    mesh_state.use_mesh()
    rng = np.random.RandomState(9)
    data = rng.bytes(256 * 32)
    sched = faults.FaultSchedule(triggers={"mesh.merkle": {1}})
    with counting() as delta:
        with faults.injected(sched):
            got = mesh_merkle.build_levels(data, 10)
    assert got is None                       # declined onto sequential
    assert sched.fully_fired()
    assert delta["mesh.merkle.fallbacks{reason=injected}"] == 1


def test_merkle_audit_catches_corruption(monkeypatch, tmp_path):
    _require_mesh()
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))
    supervisor.reset()
    mesh_state.use_mesh()
    rng = np.random.RandomState(13)
    data = rng.bytes(256 * 32)
    golden = mesh_merkle._sequential_levels(data, 10)
    sched = faults.FaultSchedule(corrupt={"mesh.merkle": [1]})
    with counting() as delta:
        with faults.injected(sched):
            got = mesh_merkle.build_levels(data, 10)
    assert sched.corrupted
    # the audit's sequential recompute is authoritative: the caller
    # still receives byte-identical levels
    assert [bytes(a) for a in got] == [bytes(b) for b in golden]
    assert delta["supervisor.quarantines{site=mesh.merkle}"] == 1
    assert supervisor.states()["mesh.merkle"] == "quarantined"
    # quarantined: the next build declines straight to sequential
    assert mesh_merkle.build_levels(data, 10) is None


def test_merkle_off_leg_declines():
    mesh_state.use_fallback()
    rng = np.random.RandomState(2)
    assert mesh_merkle.build_levels(rng.bytes(256 * 32), 10) is None


# ---------------------------------------------------------------------------
# device-loss recovery (docs/recovery.md): elastic re-shard over the
# survivors, counted reason=device_loss fallbacks, byte-identity to
# the single-device oracle
# ---------------------------------------------------------------------------

def test_device_loss_epoch_resharded_and_identical():
    """A device dropping out mid-epoch-dispatch: the handler retires
    every cached placement, rebuilds the mesh over the survivors,
    books the counted fallback and re-dispatches — byte-identical to
    the no-loss oracle."""
    _require_mesh()
    spec, state = _altair_state("altair", seed=37)
    s_ref, s_loss = state.copy(), state.copy()
    ek.use_vectorized()
    mesh_state.use_mesh()
    arrays.use_arrays()
    before = mesh_state.device_count()
    spec.process_epoch(s_ref)
    sched = faults.FaultSchedule(loss={"mesh.epoch": [1]})
    with counting() as delta:
        with faults.injected(sched):
            spec.process_epoch(s_loss)
    assert sched.losses_fired()
    assert sched.lost == [("mesh.epoch", 1)]
    assert delta["mesh.epoch.fallbacks{reason=device_loss}"] == 1
    assert delta["mesh.device_losses{site=mesh.epoch}"] == 1
    assert mesh_state.device_count() == before - 1
    # the re-dispatch over the survivors still committed all five
    # sub-transitions through the SPMD programs
    assert delta["mesh.epoch{path=mesh}"] == 5
    assert hash_tree_root(s_ref) == hash_tree_root(s_loss)


def test_device_loss_retires_cached_placements():
    """The placement epoch bump retires EVERY cell placement at once:
    a post-loss read re-places on the survivor mesh."""
    _require_mesh()
    spec, state = _altair_state("altair", seed=41)
    arrays.use_arrays()
    mesh_state.use_mesh()
    sa = arrays.of(state)
    mesh = mesh_state.build_mesh()
    with counting() as delta:
        mesh_state.sharded_cell(sa, "balances", mesh)
        mesh_state.sharded_cell(sa, "balances", mesh)   # cached
    assert delta["mesh.placements{column=balances}"] == 1
    mesh_state.lose_device("mesh.epoch")
    survivor_mesh = mesh_state.build_mesh()
    assert survivor_mesh is not mesh
    with counting() as delta:
        mesh_state.sharded_cell(sa, "balances", survivor_mesh)
    assert delta["mesh.placements{column=balances}"] == 1


def test_device_loss_merkle_resharded_and_identical():
    _require_mesh()
    mesh_state.use_mesh()
    rng = np.random.RandomState(17)
    data = rng.bytes(256 * 32)
    golden = mesh_merkle._sequential_levels(data, 10)
    sched = faults.FaultSchedule(loss={"mesh.merkle": [1]})
    with counting() as delta:
        with faults.injected(sched):
            got = mesh_merkle.build_levels(data, 10)
    assert sched.losses_fired()
    assert delta["mesh.merkle.fallbacks{reason=device_loss}"] == 1
    assert delta["mesh.device_losses{site=mesh.merkle}"] == 1
    assert got is not None, "re-shard over survivors never re-dispatched"
    assert delta["mesh.merkle{path=mesh}"] == 1
    assert [bytes(a) for a in got] == [bytes(b) for b in golden]


def test_device_loss_down_to_single_device_falls_back():
    """Losing down past the two-device gate degrades to the
    single-device engines — engagement floors respected, result
    byte-identical."""
    _require_mesh()
    spec, state = _altair_state("altair", seed=43)
    s_ref, s_lost = state.copy(), state.copy()
    ek.use_vectorized()
    mesh_state.use_mesh()
    arrays.use_arrays()
    spec.process_epoch(s_ref)
    while mesh_state.device_count() > 1:
        mesh_state.lose_device("mesh.epoch")
    assert not mesh_state.enabled()
    with counting() as delta:
        spec.process_epoch(s_lost)
    assert delta["mesh.epoch{path=mesh}"] == 0
    assert hash_tree_root(s_ref) == hash_tree_root(s_lost)


def test_restore_devices_resets_the_mesh():
    _require_mesh()
    total = mesh_state.device_count()
    mesh_state.lose_device("mesh.epoch")
    assert mesh_state.device_count() == total - 1
    mesh_state.restore_devices()
    assert mesh_state.device_count() == total
    assert len(mesh_state.active_devices()) == total


# ---------------------------------------------------------------------------
# G2 MSM mesh scaling (satellite)
# ---------------------------------------------------------------------------

def test_use_mesh_auto_derives_devices():
    _require_mesh()
    import jax
    from consensus_specs_tpu.ops import bls_rlc
    try:
        bls_rlc.use_mesh("auto")
        assert bls_rlc.mesh_devices() == tuple(jax.devices())
    finally:
        bls_rlc.use_mesh(None)
    assert bls_rlc.mesh_devices() is None


@pytest.mark.skipif(
    not __import__("consensus_specs_tpu.utils.env_flags",
                   fromlist=["HEAVY"]).HEAVY,
    reason="G2 MSM shard_map compile on a 1-core host (CS_TPU_HEAVY=1)")
def test_sharded_g2_msm_uneven_batch_matches_host():
    """Satellite: the points-sharded G2 MSM at a batch size that does
    NOT divide the mesh — identity-lane padding — equals the oracle."""
    _require_mesh()
    import jax
    import jax.numpy as jnp
    from consensus_specs_tpu.parallel.sharded_verify import (
        sharded_g2_msm_padded)
    from consensus_specs_tpu.ops import bls_jax
    from consensus_specs_tpu.ops.jax_bls import points as PT
    from consensus_specs_tpu.ops.bls12_381.curve import (
        g2_from_compressed, msm as oracle_msm)

    bls.use_py()
    sigs = [g2_from_compressed(bls.Sign(i, bytes([i]) * 32))
            for i in range(1, 7)]          # 6 points over 4 devices
    rng = np.random.RandomState(42)
    rs = [int.from_bytes(rng.bytes(16), "little") | 1 for _ in sigs]
    out = sharded_g2_msm_padded(
        PT.g2_pack(sigs),
        jnp.asarray(bls_jax._bits_msb(rs, bls_jax.RLC_SCALAR_BITS)),
        jax.devices()[:4])
    got = PT.g2_unpack(jax.tree_util.tree_map(lambda a: a[None], out))
    assert got == oracle_msm(sigs, rs)
