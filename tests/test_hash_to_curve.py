"""RFC 9380 conformance for the hash-to-G2 ciphersuite.

Vectors: RFC 9380 Appendix K.1 (expand_message_xmd, SHA-256) and
Appendix G.10.2 (suite BLS12381G2_XMD:SHA-256_SSWU_RO_).  The reference
relies on its Rust backends for this (``eth2spec/utils/bls.py:2``,
py_ecc's RFC implementation); here both the python oracle and the JAX
kernel must reproduce the IETF vectors exactly — this is what makes
emitted signatures interoperable with real Ethereum clients.
"""
import os
import sys

import pytest

from consensus_specs_tpu.utils.env_flags import HEAVY

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.ops.bls12_381 import hash_to_curve as H

XMD_DST = b"QUUX-V01-CS02-with-expander-SHA256-128"

# RFC 9380 K.1: (msg, len_in_bytes, uniform_bytes)
XMD_VECTORS = [
    (b"", 0x20,
     "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"),
    (b"abc", 0x20,
     "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"),
    (b"abcdef0123456789", 0x20,
     "eff31487c770a893cfb36f912fbfcbff40d5661771ca4b2cb4eafe524333f5c1"),
    (b"q128_" + b"q" * 128, 0x20,
     "b23a1d2b4d97b2ef7785562a7e8bac7eed54ed6e97e29aa51bfe3f12ddad1ff9"),
    (b"a512_" + b"a" * 512, 0x20,
     "4623227bcc01293b8c130bf771da8c298dede7383243dc0993d2d94823958c4c"),
]

G2_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"

# RFC 9380 G.10.2: msg -> P = hash_to_curve(msg) as (x_re, x_im, y_re, y_im)
G2_VECTORS = {
    b"": (
        0x0141ebfbdca40eb85b87142e130ab689c673cf60f1a3e98d69335266f30d9b8d4ac44c1038e9dcdd5393faf5c41fb78a,
        0x05cb8437535e20ecffaef7752baddf98034139c38452458baeefab379ba13dff5bf5dd71b72418717047f5b0f37da03d,
        0x0503921d7f6a12805e72940b963c0cf3471c7b2a524950ca195d11062ee75ec076daf2d4bc358c4b190c0c98064fdd92,
        0x12424ac32561493f3fe3c260708a12b7c620e7be00099a974e259ddc7d1f6395c3c811cdd19f1e8dbf3e9ecfdcbab8d6,
    ),
    b"abc": (
        0x02c2d18e033b960562aae3cab37a27ce00d80ccd5ba4b7fe0e7a210245129dbec7780ccc7954725f4168aff2787776e6,
        0x139cddbccdc5e91b9623efd38c49f81a6f83f175e80b06fc374de9eb4b41dfe4ca3a230ed250fbe3a2acf73a41177fd8,
        0x1787327b68159716a37440985269cf584bcb1e621d3a7202be6ea05c4cfe244aeb197642555a0645fb87bf7466b2ba48,
        0x00aa65dae3c8d732d10ecd2c50f8a1baf3001578f71c694e03866e9f3d49ac1e1ce70dd94a733534f106d4cec0eddd16,
    ),
}


def test_expand_message_xmd_rfc_vectors():
    for msg, n, expect in XMD_VECTORS:
        assert H.expand_message_xmd(msg, XMD_DST, n).hex() == expect, msg


def test_hash_to_g2_rfc_vectors_oracle():
    for msg, (xr, xi, yr, yi) in G2_VECTORS.items():
        pt = H.hash_to_g2(msg, G2_DST)
        assert (pt.x.a.n, pt.x.b.n, pt.y.a.n, pt.y.b.n) == (xr, xi, yr, yi), msg


@pytest.mark.skipif(not HEAVY, reason="jit of the hash-to-curve kernel: set CS_TPU_HEAVY=1")
def test_hash_to_g2_rfc_vectors_jax_kernel():
    """The batched device kernel must agree with the IETF vectors too."""
    from consensus_specs_tpu.ops.jax_bls import htc as HTC
    from consensus_specs_tpu.ops.jax_bls import points as PT
    import jax

    msgs = list(G2_VECTORS)
    out = HTC.hash_to_g2_batch(msgs, dst=G2_DST)
    for i, msg in enumerate(msgs):
        one = jax.tree_util.tree_map(lambda a: a[i:i + 1], out)
        pt = PT.g2_unpack(one)
        xr, xi, yr, yi = G2_VECTORS[msg]
        assert (pt.x.a.n, pt.x.b.n, pt.y.a.n, pt.y.b.n) == (xr, xi, yr, yi), msg


def test_iso_map_is_homomorphism():
    """Belt and braces beyond the import-time check: fresh sample points."""
    for tag in (b"homo-a", b"homo-b"):
        u0, u1 = H.hash_to_field_fq2(tag, 2, G2_DST)
        p = H.map_to_curve_sswu(u0)
        q = H.map_to_curve_sswu(u1)
        from consensus_specs_tpu.ops.bls12_381.curve import G2Point
        s = H._eprime_add(p, q)
        lhs = G2Point(*H.iso_map_g2(*s))
        rhs = G2Point(*H.iso_map_g2(*p)) + G2Point(*H.iso_map_g2(*q))
        assert lhs == rhs
