"""Profiling-span registry tests (SURVEY §5 tracing/profiling role)."""
import time

from consensus_specs_tpu.utils import profiling


def test_spans_disabled_by_default_are_noop():
    profiling.enable(False)
    profiling.reset()
    with profiling.span("x"):
        pass
    assert profiling.stats() == {}


def test_spans_aggregate():
    profiling.enable(True)
    profiling.reset()
    try:
        for _ in range(3):
            with profiling.span("work"):
                time.sleep(0.01)
        st = profiling.stats()["work"]
        assert st["count"] == 3
        assert st["total_s"] >= 0.03
        assert st["max_s"] >= st["mean_s"] > 0
        assert "work" in profiling.report()
    finally:
        profiling.enable(False)
        profiling.reset()
