"""Self-conformance over the ssz_generic generator corpus.

The reference ships its handcrafted wire-format cases to clients, whose
deserializers must accept/reject them (tests/formats/ssz_generic).  Here
the same corpus is driven through our own ``deserialize``: every valid
case must roundtrip byte-exactly with a matching root; every invalid
case must be rejected.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "generators", "ssz_generic"))

import main as ssz_generic_main  # noqa: E402
from consensus_specs_tpu.gen.gen_runner import RawSSZBytes  # noqa: E402
from consensus_specs_tpu.utils.ssz import (  # noqa: E402
    deserialize, serialize, hash_tree_root,
)

def _collect():
    for case in ssz_generic_main.make_cases():
        parts = dict()
        for name, value in case.case_fn():
            parts[name] = value
        yield case, parts


CASES = list(_collect())
VALID = [(c, p) for c, p in CASES if c.suite_name == "valid"]
INVALID = [(c, p) for c, p in CASES if c.suite_name == "invalid"]


def _case_type(case, parts):
    """Recover the SSZ type a case was built from (valid cases carry the
    typed value through serialize; we rebuild from the handler+name)."""
    from consensus_specs_tpu.utils.ssz import (
        uint8, uint16, uint32, uint64, uint128, uint256, boolean,
        Bitvector, Bitlist, Vector)
    h, n = case.handler_name, case.case_name
    if h == "uints":
        return {8: uint8, 16: uint16, 32: uint32, 64: uint64,
                128: uint128, 256: uint256}[int(n.split("_")[1])]
    if h == "boolean":
        return boolean
    if h == "bitvector":
        return Bitvector[int(n.split("_")[1])]
    if h == "bitlist":
        return Bitlist[int(n.split("_")[1])]
    if h == "basic_vector":
        _, ubits, length = n.split("_")[:3]
        elem = {"uint8": uint8, "uint16": uint16,
                "uint64": uint64}[ubits]
        return Vector[elem, int(length)]
    if h == "containers":
        key = n
        for suffix in ("_empty", "_short", "_long", "_offset_below_fixed_part",
                       "_offset_past_end", "_truncated", "_empty_list",
                       "_some"):
            if key.endswith(suffix):
                key = key[: -len(suffix)]
                break
        return {
            "single_field": ssz_generic_main.SingleFieldContainer,
            "small": ssz_generic_main.SmallContainer,
            "fixed": ssz_generic_main.FixedContainer,
            "var": ssz_generic_main.VarContainer,
            "complex": ssz_generic_main.ComplexContainer,
        }[key]
    raise KeyError(h)


@pytest.mark.parametrize(
    "case,parts", VALID,
    ids=[f"{c.handler_name}-{c.case_name}" for c, _ in VALID])
def test_valid_roundtrip(case, parts):
    typ = _case_type(case, parts)
    data = bytes(parts["serialized"])
    value = deserialize(typ, data)
    assert serialize(value) == data
    assert bytes(hash_tree_root(value)) == \
        bytes.fromhex(parts["root"][2:])


@pytest.mark.parametrize(
    "case,parts", INVALID,
    ids=[f"{c.handler_name}-{c.case_name}" for c, _ in INVALID])
def test_invalid_rejected(case, parts):
    typ = _case_type(case, parts)
    data = bytes(parts["serialized"])
    with pytest.raises((ValueError, AssertionError, IndexError, TypeError)):
        deserialize(typ, data)
