"""BLS signature scheme tests (python oracle backend).

Covers the edge cases the reference's ``bls`` vector suite targets
(reference: ``tests/generators/bls/main.py``): sign/verify round trips,
aggregation, wrong-key/wrong-message rejection, infinity points, tampered
and non-canonical encodings, subgroup checks.
"""
import pytest

from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.ops.bls12_381 import G1_GENERATOR, R_ORDER, pairing
from consensus_specs_tpu.ops.bls12_381.hash_to_curve import hash_to_g2
from consensus_specs_tpu.ops.bls12_381.curve import G1Point

SKS = [1, 2, 3, 12345, R_ORDER - 1]
MSG_A = b"\xab" * 32
MSG_B = b"\xcd" * 32


def setup_module():
    bls.use_py()
    bls.bls_active = True


def test_sign_verify_roundtrip():
    for sk in SKS[:3]:
        pk = bls.SkToPk(sk)
        sig = bls.Sign(sk, MSG_A)
        assert bls.Verify(pk, MSG_A, sig)
        assert not bls.Verify(pk, MSG_B, sig)
        assert not bls.Verify(bls.SkToPk(sk + 1), MSG_A, sig)


def test_tampered_signature_rejected():
    pk = bls.SkToPk(7)
    sig = bytearray(bls.Sign(7, MSG_A))
    sig[-1] ^= 1
    assert not bls.Verify(pk, MSG_A, bytes(sig))


def test_aggregate_same_message():
    pks = [bls.SkToPk(sk) for sk in SKS[:3]]
    sigs = [bls.Sign(sk, MSG_A) for sk in SKS[:3]]
    agg = bls.Aggregate(sigs)
    assert bls.FastAggregateVerify(pks, MSG_A, agg)
    assert not bls.FastAggregateVerify(pks, MSG_B, agg)
    assert not bls.FastAggregateVerify(pks[:2], MSG_A, agg)
    # aggregate pubkey equivalence
    agg_pk = bls.AggregatePKs(pks)
    assert bls.Verify(agg_pk, MSG_A, agg)


def test_aggregate_verify_distinct_messages():
    msgs = [bytes([i]) * 32 for i in range(3)]
    pks = [bls.SkToPk(sk) for sk in SKS[:3]]
    sigs = [bls.Sign(sk, m) for sk, m in zip(SKS[:3], msgs)]
    agg = bls.Aggregate(sigs)
    assert bls.AggregateVerify(pks, msgs, agg)
    assert not bls.AggregateVerify(pks, list(reversed(msgs)), agg)
    assert not bls.AggregateVerify(list(reversed(pks)), msgs, agg)


def test_empty_aggregation_invalid():
    with pytest.raises(ValueError):
        bls.Aggregate([])
    with pytest.raises(ValueError):
        bls.AggregatePKs([])
    assert not bls.FastAggregateVerify([], MSG_A, bls.Sign(1, MSG_A))
    assert not bls.AggregateVerify([], [], bls.Sign(1, MSG_A))


def test_infinity_pubkey_rejected():
    inf_pk = bytes([0xC0]) + b"\x00" * 47
    assert not bls.KeyValidate(inf_pk)
    sig = bls.Sign(1, MSG_A)
    assert not bls.Verify(inf_pk, MSG_A, sig)


def test_infinity_signature():
    inf_sig = bytes([0xC0]) + b"\x00" * 95
    pk = bls.SkToPk(5)
    assert not bls.Verify(pk, MSG_A, inf_sig)


def test_bad_encodings():
    assert not bls.KeyValidate(b"\x00" * 48)            # no compression bit
    assert not bls.KeyValidate(b"\xff" * 48)            # x >= p
    assert not bls.Verify(bls.SkToPk(1), MSG_A, b"\x00" * 96)
    assert not bls.KeyValidate(b"\x22" * 48)            # stub pubkey


def test_non_subgroup_g1_rejected():
    # find a curve point NOT in the r-order subgroup (cofactor h1 > 1)
    from consensus_specs_tpu.ops.bls12_381.fields import Fq
    from consensus_specs_tpu.ops.bls12_381.curve import B1
    x = 0
    pt = None
    while True:
        x += 1
        y = (Fq(x) * Fq(x) * Fq(x) + B1).sqrt()
        if y is None:
            continue
        cand = G1Point(Fq(x), y)
        if not cand.in_subgroup():
            pt = cand
            break
    assert not bls.KeyValidate(pt.to_compressed())


def test_bls_switch_stub():
    bls.bls_active = False
    try:
        assert bls.Sign(1, MSG_A) == bls.STUB_SIGNATURE
        assert bls.Verify(b"junk", MSG_A, b"junk")
    finally:
        bls.bls_active = True


def test_signature_matches_pairing_identity():
    # e(pk, H(m)) == e(g1, sig) directly
    sk = 42
    hm = hash_to_g2(MSG_A)
    sig_pt = hm.mult(sk)
    lhs = pairing(G1_GENERATOR.mult(sk), hm)
    rhs = pairing(G1_GENERATOR, sig_pt)
    assert lhs == rhs


def test_hash_to_g2_homomorphic_isogeny():
    # independence from representative: clear_cofactor lands in G2 always
    for m in (b"a", b"b", b"c"):
        p = hash_to_g2(m)
        assert p.mult(R_ORDER).infinity and not p.infinity
