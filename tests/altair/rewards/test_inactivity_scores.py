"""Altair reward deltas under randomized inactivity scores.

Reference model: ``test/altair/rewards/test_inactivity_scores.py``
(12 cases: random/high/half-zero score distributions x {leaking,not} x
balance profiles) against ``specs/altair/beacon-chain.md``
``get_inactivity_penalty_deltas`` / ``get_flag_index_deltas``.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_test, spec_state_test, with_phases, with_all_phases_from,
    with_custom_state, single_phase, low_balances, misc_balances,
    default_activation_threshold, zero_activation_threshold,
)
from consensus_specs_tpu.test_infra.rewards import (
    run_deltas, prepare_state_with_attestations, randomize_participation,
    set_state_in_leak,
)

ALTAIR_ONLY = with_phases(["altair"])
with_altair_and_later = with_all_phases_from("altair")


def _randomize_scores(spec, state, rng, ceiling=100):
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = rng.randrange(ceiling)


def _run_with_scores(spec, state, rng, scores_fn, leak=False,
                     participation_rng=None):
    if leak:
        set_state_in_leak(spec, state)
    scores_fn(spec, state, rng)
    participation = randomize_participation(
        participation_rng or Random(rng.randrange(1 << 30)))
    prepare_state_with_attestations(spec, state,
                                    participation_fn=participation)
    yield from run_deltas(spec, state)


@with_altair_and_later
@spec_state_test
def test_random_inactivity_scores_0(spec, state):
    yield from _run_with_scores(spec, state, Random(9999), _randomize_scores)


@ALTAIR_ONLY
@spec_state_test
def test_random_inactivity_scores_1(spec, state):
    yield from _run_with_scores(spec, state, Random(10000), _randomize_scores)


@ALTAIR_ONLY
@spec_state_test
def test_half_zero_half_random_inactivity_scores(spec, state):
    def half_zero(spec_, state_, rng):
        for i in range(len(state_.validators)):
            state_.inactivity_scores[i] = \
                rng.randrange(100) if i % 2 else 0
    yield from _run_with_scores(spec, state, Random(10101), half_zero)


@ALTAIR_ONLY
@spec_state_test
def test_random_high_inactivity_scores(spec, state):
    def high(spec_, state_, rng):
        _randomize_scores(spec_, state_, rng, ceiling=100000)
    yield from _run_with_scores(spec, state, Random(10201), high)


@ALTAIR_ONLY
@with_custom_state(low_balances, zero_activation_threshold)
@single_phase
@spec_test
def test_random_inactivity_scores_low_balances_0(spec, state):
    yield from _run_with_scores(spec, state, Random(10301), _randomize_scores)


@ALTAIR_ONLY
@with_custom_state(low_balances, zero_activation_threshold)
@single_phase
@spec_test
def test_random_inactivity_scores_low_balances_1(spec, state):
    yield from _run_with_scores(spec, state, Random(10401), _randomize_scores)


@ALTAIR_ONLY
@with_custom_state(misc_balances, default_activation_threshold)
@single_phase
@spec_test
def test_full_random_misc_balances(spec, state):
    yield from _run_with_scores(spec, state, Random(10501), _randomize_scores)


# -- leaking variants --------------------------------------------------------

@with_altair_and_later
@spec_state_test
def test_random_inactivity_scores_leaking_0(spec, state):
    yield from _run_with_scores(spec, state, Random(11111),
                                _randomize_scores, leak=True)
    assert spec.is_in_inactivity_leak(state)


@ALTAIR_ONLY
@spec_state_test
def test_random_inactivity_scores_leaking_1(spec, state):
    yield from _run_with_scores(spec, state, Random(11211),
                                _randomize_scores, leak=True)


@ALTAIR_ONLY
@spec_state_test
def test_half_zero_half_random_inactivity_scores_leaking(spec, state):
    def half_zero(spec_, state_, rng):
        for i in range(len(state_.validators)):
            state_.inactivity_scores[i] = \
                rng.randrange(100) if i % 2 else 0
    yield from _run_with_scores(spec, state, Random(11311), half_zero,
                                leak=True)


@ALTAIR_ONLY
@spec_state_test
def test_random_high_inactivity_scores_leaking(spec, state):
    def high(spec_, state_, rng):
        _randomize_scores(spec_, state_, rng, ceiling=100000)
    yield from _run_with_scores(spec, state, Random(11411), high, leak=True)


@ALTAIR_ONLY
@spec_state_test
def test_random_high_inactivity_scores_leaking_8_epochs(spec, state):
    from consensus_specs_tpu.test_infra.block import next_epoch

    def high(spec_, state_, rng):
        _randomize_scores(spec_, state_, rng, ceiling=100000)
    set_state_in_leak(spec, state)
    for _ in range(4):  # deepen the leak well past its onset
        next_epoch(spec, state)
    yield from _run_with_scores(spec, state, Random(11511), high)
    assert spec.is_in_inactivity_leak(state)
