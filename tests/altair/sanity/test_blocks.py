"""Altair whole-block sanity transitions.

Reference model: ``test/altair/sanity/test_blocks.py`` (8 cases:
sync-committee participation fractions at genesis/after an epoch,
inactivity-score evolution under leak with/without participation)
against ``specs/altair/beacon-chain.md`` ``process_block`` +
``process_sync_aggregate``.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_all_phases_from,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, next_epoch,
    state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
)
from consensus_specs_tpu.test_infra.rewards import set_state_in_leak

with_altair_and_later = with_all_phases_from("altair")
ALTAIR_ONLY = with_phases(["altair"])


def _run_sync_committee_sanity_test(spec, state, fraction_full=1.0,
                                    rng=None):
    rng = rng or Random(454545)
    committee_indices = compute_committee_indices(state)
    size = len(committee_indices)
    selected = set(rng.sample(range(size), int(size * fraction_full)))
    bits = [i in selected for i in range(size)]
    participants = [committee_indices[i] for i in range(size) if bits[i]]

    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants),
    )
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state


@with_altair_and_later
@spec_state_test
def test_sync_committee_committee__full(spec, state):
    next_epoch(spec, state)
    yield from _run_sync_committee_sanity_test(spec, state, 1.0)


@with_altair_and_later
@spec_state_test
def test_sync_committee_committee__half(spec, state):
    next_epoch(spec, state)
    yield from _run_sync_committee_sanity_test(spec, state, 0.5, Random(1212))


@with_altair_and_later
@spec_state_test
def test_sync_committee_committee__empty(spec, state):
    next_epoch(spec, state)
    yield from _run_sync_committee_sanity_test(spec, state, 0.0)


@with_altair_and_later
@spec_state_test
def test_sync_committee_committee_genesis__full(spec, state):
    yield from _run_sync_committee_sanity_test(spec, state, 1.0)


@with_altair_and_later
@spec_state_test
def test_sync_committee_committee_genesis__half(spec, state):
    yield from _run_sync_committee_sanity_test(spec, state, 0.5, Random(2323))


@with_altair_and_later
@spec_state_test
def test_sync_committee_committee_genesis__empty(spec, state):
    yield from _run_sync_committee_sanity_test(spec, state, 0.0)


@ALTAIR_ONLY
@spec_state_test
def test_inactivity_scores_leaking(spec, state):
    """Empty blocks through a leak: absent validators' scores climb."""
    set_state_in_leak(spec, state)
    yield "pre", state
    blocks = []
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, state)
        blocks.append(state_transition_and_sign_block(spec, state, block))
    yield "blocks", blocks
    yield "post", state
    assert spec.is_in_inactivity_leak(state)
    # nobody attested across the epoch boundary: every active score grew
    assert all(int(s) > 0 for s in state.inactivity_scores)


@ALTAIR_ONLY
@spec_state_test
def test_inactivity_scores_full_participation_leaking(spec, state):
    """Full previous-target participation during a leak: scores shrink
    (participation decrement applies; no recovery while leaking)."""
    set_state_in_leak(spec, state)
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = 10
        state.previous_epoch_participation[i] = spec.add_flag(
            spec.ParticipationFlags(0), spec.TIMELY_TARGET_FLAG_INDEX)
        state.current_epoch_participation[i] = spec.add_flag(
            spec.ParticipationFlags(0), spec.TIMELY_TARGET_FLAG_INDEX)
    yield "pre", state
    blocks = []
    for _ in range(spec.SLOTS_PER_EPOCH):
        block = build_empty_block_for_next_slot(spec, state)
        blocks.append(state_transition_and_sign_block(spec, state, block))
    yield "blocks", blocks
    yield "post", state
    # the epoch boundary consumed previous participation: 10 -> 9
    assert all(int(s) == 9 for s in state.inactivity_scores)
