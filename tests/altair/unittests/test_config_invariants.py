"""Altair constant/config invariants.

Reference model: ``test/altair/unittests/test_config_invariants.py``
against ``specs/altair/beacon-chain.md`` constants.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases_from,
)

with_altair_and_later = with_all_phases_from("altair")


@with_altair_and_later
@spec_state_test
def test_weight_denominator(spec, state):
    assert (
        spec.TIMELY_HEAD_WEIGHT
        + spec.TIMELY_SOURCE_WEIGHT
        + spec.TIMELY_TARGET_WEIGHT
        + spec.SYNC_REWARD_WEIGHT
        + spec.PROPOSER_WEIGHT
    ) == spec.WEIGHT_DENOMINATOR
    yield


@with_altair_and_later
@spec_state_test
def test_inactivity_score(spec, state):
    assert spec.config.INACTIVITY_SCORE_BIAS <= \
        spec.config.INACTIVITY_SCORE_RECOVERY_RATE
    yield


@with_altair_and_later
@spec_state_test
def test_flag_indices_distinct_and_weighted(spec, state):
    flags = [spec.TIMELY_SOURCE_FLAG_INDEX, spec.TIMELY_TARGET_FLAG_INDEX,
             spec.TIMELY_HEAD_FLAG_INDEX]
    assert sorted(flags) == [0, 1, 2]
    assert len(spec.PARTICIPATION_FLAG_WEIGHTS) == len(flags)
    yield


@with_altair_and_later
@spec_state_test
def test_sync_committee_period_is_epochs(spec, state):
    assert int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD) >= 1
    assert int(spec.SYNC_COMMITTEE_SIZE) % \
        int(spec.SYNC_COMMITTEE_SUBNET_COUNT) == 0
    yield
