"""``process_participation_flag_updates`` rotation coverage.

Reference model:
``test/altair/epoch_processing/test_process_participation_flag_updates.py``
(12 cases: zeroed/filled/one-side-filled/random patterns) against
``specs/altair/beacon-chain.md`` New ``process_participation_flag_updates``:
current flags rotate into previous, current resets to zero.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_all_phases_from,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.block import next_epoch

with_altair_and_later = with_all_phases_from("altair")
ALTAIR_ONLY = with_phases(["altair"])

_FULL_FLAGS = 0b111  # all three timely flags set


def _set_flags(spec, state, current_fn, previous_fn):
    for i in range(len(state.validators)):
        state.current_epoch_participation[i] = \
            spec.ParticipationFlags(current_fn(i))
        state.previous_epoch_participation[i] = \
            spec.ParticipationFlags(previous_fn(i))


def _run_rotation(spec, state):
    pre_current = [int(f) for f in state.current_epoch_participation]
    yield from run_epoch_processing_with(
        spec, state, "process_participation_flag_updates")
    # previous := old current; current := all-zero, same length
    assert [int(f) for f in state.previous_epoch_participation] == pre_current
    assert all(int(f) == 0 for f in state.current_epoch_participation)
    assert len(state.current_epoch_participation) == len(state.validators)


@with_altair_and_later
@spec_state_test
def test_all_zeroed(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, lambda i: 0, lambda i: 0)
    yield from _run_rotation(spec, state)


@with_altair_and_later
@spec_state_test
def test_filled(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, lambda i: _FULL_FLAGS, lambda i: _FULL_FLAGS)
    yield from _run_rotation(spec, state)


@with_altair_and_later
@spec_state_test
def test_previous_filled(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, lambda i: 0, lambda i: _FULL_FLAGS)
    yield from _run_rotation(spec, state)


@with_altair_and_later
@spec_state_test
def test_current_filled(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, lambda i: _FULL_FLAGS, lambda i: 0)
    yield from _run_rotation(spec, state)


def _random_flags(rng):
    return lambda i, r=rng: r.randrange(_FULL_FLAGS + 1)


@ALTAIR_ONLY
@spec_state_test
def test_random_0(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, _random_flags(Random(100)), _random_flags(Random(101)))
    yield from _run_rotation(spec, state)


@ALTAIR_ONLY
@spec_state_test
def test_random_1(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, _random_flags(Random(200)), _random_flags(Random(201)))
    yield from _run_rotation(spec, state)


@ALTAIR_ONLY
@spec_state_test
def test_random_2(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, _random_flags(Random(300)), _random_flags(Random(301)))
    yield from _run_rotation(spec, state)


@ALTAIR_ONLY
@spec_state_test
def test_random_genesis(spec, state):
    # rotation happens at genesis epoch too (no short-circuit here)
    _set_flags(spec, state, _random_flags(Random(400)), _random_flags(Random(401)))
    yield from _run_rotation(spec, state)


@with_altair_and_later
@spec_state_test
def test_current_epoch_zeroed(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, lambda i: 0, _random_flags(Random(500)))
    yield from _run_rotation(spec, state)


@with_altair_and_later
@spec_state_test
def test_previous_epoch_zeroed(spec, state):
    next_epoch(spec, state)
    _set_flags(spec, state, _random_flags(Random(600)), lambda i: 0)
    yield from _run_rotation(spec, state)


@ALTAIR_ONLY
@spec_state_test
def test_single_flag_patterns(spec, state):
    """Each validator carries exactly one distinct flag bit."""
    next_epoch(spec, state)
    _set_flags(spec, state,
               lambda i: 1 << (i % 3),
               lambda i: 1 << ((i + 1) % 3))
    yield from _run_rotation(spec, state)


@ALTAIR_ONLY
@spec_state_test
def test_rotation_is_value_copy_not_alias(spec, state):
    """Mutating current after rotation must not leak into previous."""
    next_epoch(spec, state)
    _set_flags(spec, state, lambda i: _FULL_FLAGS, lambda i: 0)
    yield from run_epoch_processing_with(
        spec, state, "process_participation_flag_updates")
    state.current_epoch_participation[0] = spec.ParticipationFlags(0b010)
    assert int(state.previous_epoch_participation[0]) == _FULL_FLAGS
