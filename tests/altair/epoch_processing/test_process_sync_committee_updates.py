"""``process_sync_committee_updates`` period-boundary coverage.

Reference model:
``test/altair/epoch_processing/test_process_sync_committee_updates.py``
(5 cases: progress at genesis/non-genesis period boundaries, misc
balances, no progress off-boundary) against
``specs/altair/beacon-chain.md`` New ``process_sync_committee_updates``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_test, with_phases, with_all_phases_from, with_custom_state,
    single_phase, spec_state_test, misc_balances,
    default_activation_threshold,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.block import next_epoch

with_altair_and_later = with_all_phases_from("altair")
ALTAIR_ONLY = with_phases(["altair"])


def _transition_to_period_end(spec, state):
    """Advance so the NEXT epoch starts a new sync-committee period."""
    period = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    while (spec.get_current_epoch(state) + 1) % period != 0:
        next_epoch(spec, state)


def _run_committees_progress_test(spec, state):
    pre_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    # rotation: next becomes current; a fresh committee is drawn for next
    assert state.current_sync_committee == pre_next
    # the new next committee is a valid draw for the upcoming period
    assert len(state.next_sync_committee.pubkeys) == \
        spec.SYNC_COMMITTEE_SIZE
    registry_pubkeys = set(bytes(v.pubkey) for v in state.validators)
    assert all(bytes(p) in registry_pubkeys
               for p in state.next_sync_committee.pubkeys)


@with_altair_and_later
@spec_state_test
def test_sync_committees_progress_genesis(spec, state):
    # genesis sits one epoch before the first period boundary on minimal
    _transition_to_period_end(spec, state)
    yield from _run_committees_progress_test(spec, state)


@with_altair_and_later
@spec_state_test
def test_sync_committees_progress_not_genesis(spec, state):
    next_epoch(spec, state)
    _transition_to_period_end(spec, state)
    yield from _run_committees_progress_test(spec, state)


@ALTAIR_ONLY
@with_custom_state(misc_balances, default_activation_threshold)
@single_phase
@spec_test
def test_sync_committees_progress_misc_balances_genesis(spec, state):
    _transition_to_period_end(spec, state)
    yield from _run_committees_progress_test(spec, state)


@ALTAIR_ONLY
@with_custom_state(misc_balances, default_activation_threshold)
@single_phase
@spec_test
def test_sync_committees_progress_misc_balances_not_genesis(spec, state):
    next_epoch(spec, state)
    _transition_to_period_end(spec, state)
    yield from _run_committees_progress_test(spec, state)


@with_altair_and_later
@spec_state_test
def test_sync_committees_no_progress_not_at_period_boundary(spec, state):
    period = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    assert period > 1, "minimal preset period must exceed one epoch"
    next_epoch(spec, state)
    assert (spec.get_current_epoch(state) + 1) % period != 0
    pre_current = state.current_sync_committee.copy()
    pre_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    # off-boundary: both committees unchanged
    assert state.current_sync_committee == pre_current
    assert state.next_sync_committee == pre_next
