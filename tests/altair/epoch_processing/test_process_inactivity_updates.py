"""``process_inactivity_updates`` boundary coverage.

Reference model:
``test/altair/epoch_processing/test_process_inactivity_updates.py``
(21 cases: genesis short-circuit; {zero,random} pre-scores x
{empty,random,full} previous-target participation x {leaking,not};
slashed-validator variants) against
``specs/altair/beacon-chain.md`` New ``process_inactivity_updates``.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_all_phases_from,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.test_infra.rewards import set_state_in_leak

with_altair_and_later = with_all_phases_from("altair")
ALTAIR_ONLY = with_phases(["altair"])


def _set_previous_target_participation(spec, state, selector):
    """selector(index) -> bool decides previous-epoch target participation."""
    for i in range(len(state.validators)):
        flag = spec.ParticipationFlags(0)
        if selector(i):
            flag = spec.add_flag(flag, spec.TIMELY_TARGET_FLAG_INDEX)
        state.previous_epoch_participation[i] = flag


def _expected_scores(spec, state):
    """Independent re-derivation of the spec update rule."""
    participating = spec.get_unslashed_participating_indices(
        state, spec.TIMELY_TARGET_FLAG_INDEX, spec.get_previous_epoch(state))
    eligible = set(spec.get_eligible_validator_indices(state))
    leaking = spec.is_in_inactivity_leak(state)
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    recovery = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    out = []
    for i, score in enumerate(state.inactivity_scores):
        score = int(score)
        if i in eligible:
            if i in participating:
                score -= min(1, score)
            else:
                score += bias
            if not leaking:
                score -= min(recovery, score)
        out.append(score)
    return out


def _run_inactivity_scores_test(spec, state, selector,
                                scores_fn=None):
    # two epochs in so previous-epoch accounting is live
    next_epoch(spec, state)
    next_epoch(spec, state)
    if scores_fn is not None:
        for i in range(len(state.validators)):
            state.inactivity_scores[i] = scores_fn(i)
    _set_previous_target_participation(spec, state, selector)
    expected = _expected_scores(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert [int(s) for s in state.inactivity_scores] == expected


def _run_leaking_inactivity_scores_test(spec, state, selector,
                                        scores_fn=None):
    set_state_in_leak(spec, state)
    if scores_fn is not None:
        for i in range(len(state.validators)):
            state.inactivity_scores[i] = scores_fn(i)
    _set_previous_target_participation(spec, state, selector)
    expected = _expected_scores(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert [int(s) for s in state.inactivity_scores] == expected


def _random_scores(rng, ceiling=100):
    return lambda i, r=rng: r.randrange(ceiling)


def _random_selector(rng, fraction=0.5):
    return lambda i, r=rng: r.random() < fraction


# -- genesis short-circuit ---------------------------------------------------

@with_altair_and_later
@spec_state_test
def test_genesis(spec, state):
    """At GENESIS_EPOCH the stage is a no-op regardless of participation."""
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    _set_previous_target_participation(spec, state, lambda i: False)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert all(int(s) == 0 for s in state.inactivity_scores)


@with_altair_and_later
@spec_state_test
def test_genesis_random_scores(spec, state):
    assert spec.get_current_epoch(state) == spec.GENESIS_EPOCH
    rng = Random(10102)
    pre = [rng.randrange(100) for _ in range(len(state.validators))]
    for i, s in enumerate(pre):
        state.inactivity_scores[i] = s
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    # untouched: the genesis short-circuit fires before any mutation
    assert [int(s) for s in state.inactivity_scores] == pre


# -- all-zero pre-scores -----------------------------------------------------

@with_altair_and_later
@spec_state_test
def test_all_zero_inactivity_scores_empty_participation(spec, state):
    yield from _run_inactivity_scores_test(
        spec, state, lambda i: False, scores_fn=lambda i: 0)


@ALTAIR_ONLY
@spec_state_test
def test_all_zero_inactivity_scores_empty_participation_leaking(spec, state):
    yield from _run_leaking_inactivity_scores_test(
        spec, state, lambda i: False, scores_fn=lambda i: 0)
    # absent while leaking: every eligible score grew by exactly BIAS
    eligible = set(spec.get_eligible_validator_indices(state))
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    assert all(int(state.inactivity_scores[i]) == bias for i in eligible)


@with_altair_and_later
@spec_state_test
def test_all_zero_inactivity_scores_random_participation(spec, state):
    yield from _run_inactivity_scores_test(
        spec, state, _random_selector(Random(5555)), scores_fn=lambda i: 0)


@ALTAIR_ONLY
@spec_state_test
def test_all_zero_inactivity_scores_random_participation_leaking(spec, state):
    yield from _run_leaking_inactivity_scores_test(
        spec, state, _random_selector(Random(5565)), scores_fn=lambda i: 0)


@with_altair_and_later
@spec_state_test
def test_all_zero_inactivity_scores_full_participation(spec, state):
    yield from _run_inactivity_scores_test(
        spec, state, lambda i: True, scores_fn=lambda i: 0)
    assert all(int(s) == 0 for s in state.inactivity_scores)


@ALTAIR_ONLY
@spec_state_test
def test_all_zero_inactivity_scores_full_participation_leaking(spec, state):
    yield from _run_leaking_inactivity_scores_test(
        spec, state, lambda i: True, scores_fn=lambda i: 0)
    # participating with zero score: stays zero even while leaking
    assert all(int(s) == 0 for s in state.inactivity_scores)


# -- random pre-scores -------------------------------------------------------

@with_altair_and_later
@spec_state_test
def test_random_inactivity_scores_empty_participation(spec, state):
    yield from _run_inactivity_scores_test(
        spec, state, lambda i: False, _random_scores(Random(9999)))


@ALTAIR_ONLY
@spec_state_test
def test_random_inactivity_scores_empty_participation_leaking(spec, state):
    yield from _run_leaking_inactivity_scores_test(
        spec, state, lambda i: False, _random_scores(Random(9989)))


@with_altair_and_later
@spec_state_test
def test_random_inactivity_scores_random_participation(spec, state):
    yield from _run_inactivity_scores_test(
        spec, state, _random_selector(Random(22222)),
        _random_scores(Random(22)))


@ALTAIR_ONLY
@spec_state_test
def test_random_inactivity_scores_random_participation_leaking(spec, state):
    yield from _run_leaking_inactivity_scores_test(
        spec, state, _random_selector(Random(22322)),
        _random_scores(Random(23)))


@with_altair_and_later
@spec_state_test
def test_random_inactivity_scores_full_participation(spec, state):
    yield from _run_inactivity_scores_test(
        spec, state, lambda i: True, _random_scores(Random(33333)))


@ALTAIR_ONLY
@spec_state_test
def test_random_inactivity_scores_full_participation_leaking(spec, state):
    yield from _run_leaking_inactivity_scores_test(
        spec, state, lambda i: True, _random_scores(Random(33433)))
    # leaking but participating: each score only ever decremented by 1
    # (no recovery subtraction fires during a leak)


# -- slashed-validator variants ---------------------------------------------

def _slash_some(spec, state, rng=None):
    """Slash a handful of validators; they are excluded from
    'unslashed participating' regardless of their flags."""
    rng = rng or Random(40404)
    count = max(1, len(state.validators) // 8)
    slashed = rng.sample(range(len(state.validators)), count)
    for index in slashed:
        spec.slash_validator(state, spec.ValidatorIndex(index))
    return slashed


@ALTAIR_ONLY
@spec_state_test
def test_some_slashed_zero_scores_full_participation(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    slashed = _slash_some(spec, state)
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = 0
    _set_previous_target_participation(spec, state, lambda i: True)
    expected = _expected_scores(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert [int(s) for s in state.inactivity_scores] == expected
    # slashed validators count as absent: their score grew
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    recovery = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    grown = max(0, bias - recovery)
    for i in slashed:
        assert int(state.inactivity_scores[i]) == grown


@ALTAIR_ONLY
@spec_state_test
def test_some_slashed_zero_scores_full_participation_leaking(spec, state):
    set_state_in_leak(spec, state)
    slashed = _slash_some(spec, state, Random(40414))
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = 0
    _set_previous_target_participation(spec, state, lambda i: True)
    expected = _expected_scores(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert [int(s) for s in state.inactivity_scores] == expected
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    for i in slashed:
        # slashed + leaking: full BIAS growth, no recovery
        assert int(state.inactivity_scores[i]) == bias


@ALTAIR_ONLY
@spec_state_test
def test_some_slashed_random_scores_random_participation(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    _slash_some(spec, state, Random(40424))
    rng = Random(40434)
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = rng.randrange(100)
    _set_previous_target_participation(spec, state,
                                       _random_selector(Random(40444)))
    expected = _expected_scores(spec, state)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert [int(s) for s in state.inactivity_scores] == expected


# -- boundary values ---------------------------------------------------------

@ALTAIR_ONLY
@spec_state_test
def test_score_at_exactly_recovery_rate(spec, state):
    """score == RECOVERY_RATE drains to zero in one participating epoch."""
    next_epoch(spec, state)
    next_epoch(spec, state)
    rate = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    for i in range(len(state.validators)):
        # +1 first cancels the participation decrement
        state.inactivity_scores[i] = rate + 1
    _set_previous_target_participation(spec, state, lambda i: True)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    eligible = set(spec.get_eligible_validator_indices(state))
    assert all(int(state.inactivity_scores[i]) == 0 for i in eligible)


@ALTAIR_ONLY
@spec_state_test
def test_score_one_above_full_recovery(spec, state):
    """score = RECOVERY + 2 participating: floor at 1 above the drain."""
    next_epoch(spec, state)
    next_epoch(spec, state)
    rate = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = rate + 2
    _set_previous_target_participation(spec, state, lambda i: True)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    eligible = set(spec.get_eligible_validator_indices(state))
    assert all(int(state.inactivity_scores[i]) == 1 for i in eligible)


@ALTAIR_ONLY
@spec_state_test
def test_score_never_negative(spec, state):
    """min() clamps stop the unsigned scores underflowing at 0/1."""
    next_epoch(spec, state)
    next_epoch(spec, state)
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = i % 2  # zeros and ones
    _set_previous_target_participation(spec, state, lambda i: True)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    assert all(int(s) >= 0 for s in state.inactivity_scores)
    eligible = set(spec.get_eligible_validator_indices(state))
    assert all(int(state.inactivity_scores[i]) == 0 for i in eligible)
