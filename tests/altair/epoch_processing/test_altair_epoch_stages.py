"""Altair-specific epoch sub-transitions.

Reference model: ``test/altair/epoch_processing/`` —
``process_inactivity_updates``, ``process_participation_flag_updates``,
``process_sync_committee_updates`` against
``specs/altair/beacon-chain.md``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)
from consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from consensus_specs_tpu.test_infra.block import next_epoch

ALTAIR_PLUS = ["altair", "bellatrix", "capella", "deneb"]


def _set_full_previous_target_participation(spec, state, participate=True):
    flag = spec.ParticipationFlags(0)
    if participate:
        flag = spec.add_flag(flag, spec.TIMELY_TARGET_FLAG_INDEX)
    for i in range(len(state.validators)):
        state.previous_epoch_participation[i] = flag


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_inactivity_scores_decrease_when_participating(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = 10
    _set_full_previous_target_participation(spec, state, True)
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    # -1 for participating, then recovery-rate decrement (not leaking)
    expected = 10 - 1 - min(10 - 1, spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    assert all(int(s) == expected for s in state.inactivity_scores)


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_inactivity_scores_increase_when_absent(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    _set_full_previous_target_participation(spec, state, False)
    pre = [int(s) for s in state.inactivity_scores]
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    # +BIAS for absence, then recovery decrement while not leaking
    bias = spec.config.INACTIVITY_SCORE_BIAS
    rec = spec.config.INACTIVITY_SCORE_RECOVERY_RATE
    for before, after in zip(pre, state.inactivity_scores):
        assert int(after) == before + bias - min(before + bias, rec)


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_inactivity_scores_no_recovery_during_leak(spec, state):
    # force a leak: finalized checkpoint far behind
    next_epoch(spec, state)
    for _ in range(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    _set_full_previous_target_participation(spec, state, False)
    pre = [int(s) for s in state.inactivity_scores]
    yield from run_epoch_processing_with(
        spec, state, "process_inactivity_updates")
    bias = spec.config.INACTIVITY_SCORE_BIAS
    for before, after in zip(pre, state.inactivity_scores):
        assert int(after) == before + bias




@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_sync_committee_stable_mid_period(spec, state):
    pre_current = state.current_sync_committee.copy()
    pre_next = state.next_sync_committee.copy()
    next_epoch(spec, state)
    # rotation triggers when (current + 1) % period == 0 — rule THAT out
    assert (spec.get_current_epoch(state) + 1) % \
        spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD != 0
    yield from run_epoch_processing_with(
        spec, state, "process_sync_committee_updates")
    assert state.current_sync_committee == pre_current
    assert state.next_sync_committee == pre_next
