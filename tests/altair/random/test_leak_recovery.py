"""Inactivity-leak entry and finality recovery, driven organically.

Before this suite, nothing drove a chain into the leak through block
processing: ``randomize_state`` scatters scores onto a finalizing chain
and ``set_state_in_leak`` rewrites checkpoints directly, so the leak
arm of epoch processing (score growth, quotient-scaled penalties,
recovery decrement) never ran against state the chain itself produced.
``run_leak_recovery_scenario`` (``test_infra/random_scenarios.py``)
stalls finality with sub-2/3 blocks until ``is_in_inactivity_leak``,
holds it while scores grow, then recovers to an advanced finalized
checkpoint — asserting each milestone — across every altair+ fork,
with a byte-identity leg against the spec loops (``CS_TPU_*=0``).
"""
import os

import pytest

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases_from, with_phases, pytest_only,
)
from consensus_specs_tpu.test_infra.random_scenarios import (
    run_leak_recovery_scenario,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root

# the canonical engines-off switch map the harness's spec-differential
# legs use; the switches all live-read their variables (env_flags.py)
from consensus_specs_tpu.sim.harness import ENGINES_OFF as _ENGINES_OFF


@with_all_phases_from("altair")
@spec_state_test
def test_leak_entry_and_finality_recovery(spec, state):
    """The chain leaks and recovers on every altair+ fork; every
    milestone assert lives in the scenario helper."""
    yield "pre", state
    blocks = run_leak_recovery_scenario(spec, state, seed=8800)
    yield "blocks", blocks
    yield "post", state


@pytest.mark.slow
@with_all_phases_from("altair")
@spec_state_test
def test_leak_recovery_alternate_participation(spec, state):
    """A deeper stall (40% participation) must still leak and recover.
    A second full sweep across the fork matrix: outside the tier-1
    budget, run by the CI adversarial-sim job and the generator."""
    yield "pre", state
    blocks = run_leak_recovery_scenario(spec, state, seed=8801,
                                        participation=0.4)
    yield "blocks", blocks
    yield "post", state


@with_phases(["altair", "deneb"])
@spec_state_test
@pytest_only
def test_leak_recovery_engines_differential(spec, state):
    """The same leak/recovery replay with every accelerated engine off
    must produce byte-identical blocks and post-state — the leak arm is
    exactly where the vectorized inactivity/rewards kernels diverge
    from the spec loops if they ever will."""
    s_on = state.copy()
    blocks_on = run_leak_recovery_scenario(spec, s_on, seed=8802)

    saved = {k: os.environ.get(k) for k in _ENGINES_OFF}
    os.environ.update(_ENGINES_OFF)
    try:
        s_off = state.copy()
        blocks_off = run_leak_recovery_scenario(spec, s_off, seed=8802)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    assert bytes(hash_tree_root(s_on)) == bytes(hash_tree_root(s_off))
    assert [bytes(hash_tree_root(b)) for b in blocks_on] \
        == [bytes(hash_tree_root(b)) for b in blocks_off]
    yield
