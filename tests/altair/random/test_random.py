"""Altair-specific seeded randomized scenarios.

Reference model: ``test/altair/random/test_random.py`` (16 seeded
scenarios mixing leak/no-leak states, random blocks with sync
aggregates) compiled from ``test/utils/randomized_block_tests.py``.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
    next_epoch,
)
from consensus_specs_tpu.test_infra.random_scenarios import (
    run_random_scenario, randomize_state,
)
from consensus_specs_tpu.test_infra.rewards import set_state_in_leak
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
)

ALTAIR_ONLY = with_phases(["altair"])


def _random_sync_aggregate_block(spec, state, rng):
    """A block carrying a random-participation sync aggregate."""
    committee_indices = compute_committee_indices(state)
    size = len(committee_indices)
    selected = set(rng.sample(range(size), rng.randrange(size + 1)))
    bits = [i in selected for i in range(size)]
    participants = [committee_indices[i] for i in range(size) if bits[i]]
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants),
    )
    return state_transition_and_sign_block(spec, state, block)


def _run_sync_scenario(spec, state, seed, epochs=1, leak=False):
    rng = Random(seed)
    if leak:
        set_state_in_leak(spec, state)
    else:
        next_epoch(spec, state)
        next_epoch(spec, state)
    randomize_state(spec, state, rng, exit_fraction=0.02,
                    slash_fraction=0.02)
    yield "pre", state
    blocks = []
    for _ in range(epochs * 4):
        blocks.append(_random_sync_aggregate_block(spec, state, rng))
    yield "blocks", blocks
    yield "post", state


@ALTAIR_ONLY
@spec_state_test
def test_random_scenario_0(spec, state):
    yield "pre", state
    blocks = run_random_scenario(spec, state, seed=5510)
    yield "blocks", blocks
    yield "post", state


@ALTAIR_ONLY
@spec_state_test
def test_random_scenario_1(spec, state):
    yield "pre", state
    blocks = run_random_scenario(spec, state, seed=5511)
    yield "blocks", blocks
    yield "post", state


@ALTAIR_ONLY
@spec_state_test
def test_random_sync_aggregates_0(spec, state):
    yield from _run_sync_scenario(spec, state, seed=6600)


@ALTAIR_ONLY
@spec_state_test
def test_random_sync_aggregates_1(spec, state):
    yield from _run_sync_scenario(spec, state, seed=6601)


@ALTAIR_ONLY
@spec_state_test
def test_random_sync_aggregates_leak(spec, state):
    yield from _run_sync_scenario(spec, state, seed=6602, leak=True)


@ALTAIR_ONLY
@spec_state_test
def test_random_sync_aggregates_two_epochs(spec, state):
    yield from _run_sync_scenario(spec, state, seed=6603, epochs=2)


@ALTAIR_ONLY
@spec_state_test
def test_random_with_exits_and_slashings(spec, state):
    rng = Random(6604)
    next_epoch(spec, state)
    next_epoch(spec, state)
    randomize_state(spec, state, rng, exit_fraction=0.15,
                    slash_fraction=0.15)
    yield "pre", state
    blocks = [_random_sync_aggregate_block(spec, state, rng)
              for _ in range(4)]
    yield "blocks", blocks
    yield "post", state


@ALTAIR_ONLY
@spec_state_test
def test_random_leak_recovery(spec, state):
    """Enter a leak, then give full participation: epoch processing must
    walk scores back down without underflow."""
    rng = Random(6605)
    set_state_in_leak(spec, state)
    yield "pre", state
    flag = spec.add_flag(spec.ParticipationFlags(0),
                         spec.TIMELY_TARGET_FLAG_INDEX)
    for i in range(len(state.validators)):
        state.previous_epoch_participation[i] = flag
        state.current_epoch_participation[i] = flag
    blocks = []
    for _ in range(2 * spec.SLOTS_PER_EPOCH):
        blocks.append(_random_sync_aggregate_block(spec, state, rng))
    yield "blocks", blocks
    yield "post", state
    assert all(int(s) >= 0 for s in state.inactivity_scores)
