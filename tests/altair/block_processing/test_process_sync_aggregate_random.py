"""Randomized sync-aggregate participation coverage.

Reference model:
``test/altair/block_processing/sync_aggregate/test_process_sync_aggregate_random.py``
(12 cases: participation fractions x {duplicate,nonduplicate} committee
membership, misc balances, exited members) against
``specs/altair/beacon-chain.md`` ``process_sync_aggregate``.

The "_with_duplicates" variants pin the registry to HALF the sync
committee size, so ``get_next_sync_committee_indices`` must wrap its
candidate walk and every member holds multiple committee positions —
exercising the repeated reward/penalty application path. The
"_without_duplicates" variants run on the default 64-validator registry,
whose 32 accepted draws are distinct.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_test, spec_state_test, with_phases, with_all_phases_from,
    with_custom_state, single_phase, misc_balances,
    default_activation_threshold,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, next_epoch,
)
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
    run_sync_committee_processing,
)

with_altair_and_later = with_all_phases_from("altair")
ALTAIR_ONLY = with_phases(["altair"])


def half_committee_balances(spec):
    """Registry of SYNC_COMMITTEE_SIZE // 2 validators: the committee
    draw must wrap, so every member appears at least twice."""
    return [spec.MAX_EFFECTIVE_BALANCE] * (int(spec.SYNC_COMMITTEE_SIZE) // 2)


def _run_random_participation(spec, state, fraction, rng,
                              exit_some=False, expect_duplicates=False):
    committee_indices = compute_committee_indices(state)
    size = len(committee_indices)
    if expect_duplicates:
        assert len(set(committee_indices)) < size, \
            "fixture must produce duplicate committee membership"
    if exit_some:
        # initiate exits for a few members; they still serve the current
        # period and their signatures still count
        for index in set(committee_indices[:max(1, size // 8)]):
            spec.initiate_validator_exit(state, spec.ValidatorIndex(index))
    selected = set(rng.sample(range(size), int(size * fraction)))
    bits = [i in selected for i in range(size)]
    participants = [committee_indices[i] for i in range(size) if bits[i]]

    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants),
    )
    spec.process_slots(state, block.slot)

    # per-validator position counts: rewards/penalties apply once PER
    # POSITION, so a duplicated member's net delta follows the sign of
    # (participating positions - absent positions)
    from collections import Counter
    pos_participating = Counter(committee_indices[i]
                                for i in range(size) if bits[i])
    pos_absent = Counter(committee_indices[i]
                         for i in range(size) if not bits[i])
    balances_pre = {i: int(state.balances[i]) for i in committee_indices}
    proposer = spec.get_beacon_proposer_index(state)
    yield from run_sync_committee_processing(spec, state, block)
    for index in set(committee_indices):
        if index == proposer:
            continue  # proposer gains its cut on top of its slot deltas
        delta = int(state.balances[index]) - balances_pre[index]
        net_positions = pos_participating[index] - pos_absent[index]
        if net_positions > 0:
            assert delta >= 0
        elif net_positions < 0:
            assert delta <= 0
        else:
            assert delta == 0  # equal rewards and penalties cancel


def _distinct_only_bits(spec, state, rng, fraction):
    """Participation over the DISTINCT committee members only."""
    committee_indices = compute_committee_indices(state)
    distinct = sorted(set(committee_indices))
    chosen = set(rng.sample(distinct, int(len(distinct) * fraction)))
    bits = [committee_indices[i] in chosen
            for i in range(len(committee_indices))]
    participants = [committee_indices[i]
                    for i in range(len(committee_indices)) if bits[i]]
    return bits, participants


def _run_distinct_participation(spec, state, fraction, rng):
    bits, participants = _distinct_only_bits(spec, state, rng, fraction)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, participants),
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block)


# -- with duplicates (registry smaller than the committee) ------------------

@with_altair_and_later
@with_custom_state(half_committee_balances, default_activation_threshold)
@single_phase
@spec_test
def test_random_only_one_participant_with_duplicates(spec, state):
    committee_indices = compute_committee_indices(state)
    yield from _run_random_participation(
        spec, state, 1 / len(committee_indices), Random(101),
        expect_duplicates=True)


@with_altair_and_later
@with_custom_state(half_committee_balances, default_activation_threshold)
@single_phase
@spec_test
def test_random_low_participation_with_duplicates(spec, state):
    yield from _run_random_participation(spec, state, 0.25, Random(201),
                                         expect_duplicates=True)


@with_altair_and_later
@with_custom_state(half_committee_balances, default_activation_threshold)
@single_phase
@spec_test
def test_random_high_participation_with_duplicates(spec, state):
    yield from _run_random_participation(spec, state, 0.75, Random(301),
                                         expect_duplicates=True)


@with_altair_and_later
@with_custom_state(half_committee_balances, default_activation_threshold)
@single_phase
@spec_test
def test_random_all_but_one_participating_with_duplicates(spec, state):
    committee_indices = compute_committee_indices(state)
    size = len(committee_indices)
    yield from _run_random_participation(
        spec, state, (size - 1) / size, Random(401),
        expect_duplicates=True)


@ALTAIR_ONLY
@with_custom_state(half_committee_balances, default_activation_threshold)
@single_phase
@spec_test
def test_random_misc_balances_and_half_participation_with_duplicates(
        spec, state):
    # vary effective balances across the small registry too
    rng = Random(511)
    for i in range(len(state.validators)):
        bal = spec.MAX_EFFECTIVE_BALANCE - rng.randrange(2) \
            * spec.EFFECTIVE_BALANCE_INCREMENT
        state.validators[i].effective_balance = bal
    yield from _run_random_participation(spec, state, 0.5, Random(501),
                                         expect_duplicates=True)


@ALTAIR_ONLY
@with_custom_state(half_committee_balances, default_activation_threshold)
@single_phase
@spec_test
def test_random_with_exits_with_duplicates(spec, state):
    next_epoch(spec, state)
    yield from _run_random_participation(spec, state, 0.5, Random(601),
                                         exit_some=True,
                                         expect_duplicates=True)


# -- without duplicates (distinct-member subset) ----------------------------

@ALTAIR_ONLY
@spec_state_test
def test_random_only_one_participant_without_duplicates(spec, state):
    committee_indices = compute_committee_indices(state)
    distinct = len(set(committee_indices))
    yield from _run_distinct_participation(
        spec, state, 1 / distinct, Random(701))


@ALTAIR_ONLY
@spec_state_test
def test_random_low_participation_without_duplicates(spec, state):
    yield from _run_distinct_participation(spec, state, 0.25, Random(801))


@ALTAIR_ONLY
@spec_state_test
def test_random_high_participation_without_duplicates(spec, state):
    yield from _run_distinct_participation(spec, state, 0.75, Random(901))


@ALTAIR_ONLY
@spec_state_test
def test_random_all_but_one_participating_without_duplicates(spec, state):
    committee_indices = compute_committee_indices(state)
    distinct = len(set(committee_indices))
    yield from _run_distinct_participation(
        spec, state, (distinct - 1) / distinct, Random(1001))


@ALTAIR_ONLY
@with_custom_state(misc_balances, default_activation_threshold)
@single_phase
@spec_test
def test_random_misc_balances_and_half_participation_without_duplicates(
        spec, state):
    yield from _run_distinct_participation(spec, state, 0.5, Random(1101))


@ALTAIR_ONLY
@spec_state_test
def test_random_with_exits_without_duplicates(spec, state):
    next_epoch(spec, state)
    committee_indices = compute_committee_indices(state)
    for index in sorted(set(committee_indices))[:2]:
        spec.initiate_validator_exit(state, spec.ValidatorIndex(index))
    yield from _run_distinct_participation(spec, state, 0.5, Random(1201))
