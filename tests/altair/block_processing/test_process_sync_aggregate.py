"""Sync-aggregate processing tests.

Reference model: ``test/altair/block_processing/sync_aggregate/``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases_from, always_bls, never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot)
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
    run_sync_committee_processing,
)

with_altair_and_later = with_all_phases_from("altair")


@with_altair_and_later
@spec_state_test
def test_sync_committee_rewards_all_participating(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices),
    )
    spec.process_slots(state, block.slot)
    pre_balances = [int(state.balances[i]) for i in committee_indices]
    yield from run_sync_committee_processing(spec, state, block)
    post_balances = [int(state.balances[i]) for i in committee_indices]
    assert all(post >= pre for pre, post in zip(pre_balances, post_balances))


@with_altair_and_later
@spec_state_test
def test_sync_committee_nonparticipating_penalized(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    half = len(committee_indices) // 2
    bits = [i < half for i in range(len(committee_indices))]
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices[:half]),
    )
    spec.process_slots(state, block.slot)
    nonparticipant = committee_indices[-1]
    pre = int(state.balances[nonparticipant])
    yield from run_sync_committee_processing(spec, state, block)
    assert int(state.balances[nonparticipant]) < pre


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_bad_domain(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        # signed over the wrong block root
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices,
            block_root=spec.Root(b"\x42" * 32)),
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block,
                                             expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_missing_participant(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    # all bits set, but one participant missing from the signature
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices[:-1]),
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block,
                                             expect_exception=True)


@with_altair_and_later
@spec_state_test
@never_bls
def test_empty_sync_aggregate_infinity_sig(spec, state):
    """All-zero bits with the infinity signature is valid (bls.md:61)."""
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[False] * len(committee_indices),
        sync_committee_signature=spec.G2_POINT_AT_INFINITY,
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block)
