"""Sync-aggregate processing tests.

Reference model: ``test/altair/block_processing/sync_aggregate/``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases_from, always_bls, never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot)
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
    run_sync_committee_processing,
)

with_altair_and_later = with_all_phases_from("altair")


@with_altair_and_later
@spec_state_test
def test_sync_committee_rewards_all_participating(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices),
    )
    spec.process_slots(state, block.slot)
    pre_balances = [int(state.balances[i]) for i in committee_indices]
    yield from run_sync_committee_processing(spec, state, block)
    post_balances = [int(state.balances[i]) for i in committee_indices]
    assert all(post >= pre for pre, post in zip(pre_balances, post_balances))


@with_altair_and_later
@spec_state_test
def test_sync_committee_nonparticipating_penalized(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    half = len(committee_indices) // 2
    bits = [i < half for i in range(len(committee_indices))]
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices[:half]),
    )
    spec.process_slots(state, block.slot)
    nonparticipant = committee_indices[-1]
    pre = int(state.balances[nonparticipant])
    yield from run_sync_committee_processing(spec, state, block)
    assert int(state.balances[nonparticipant]) < pre


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_bad_domain(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        # signed over the wrong block root
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices,
            block_root=spec.Root(b"\x42" * 32)),
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block,
                                             expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_missing_participant(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    # all bits set, but one participant missing from the signature
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices[:-1]),
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block,
                                             expect_exception=True)


@with_altair_and_later
@spec_state_test
@never_bls
def test_empty_sync_aggregate_infinity_sig(spec, state):
    """All-zero bits with the infinity signature is valid (bls.md:61)."""
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[False] * len(committee_indices),
        sync_committee_signature=spec.G2_POINT_AT_INFINITY,
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_extra_participant(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    # one bit cleared, but the "absent" member still signed
    bits = [True] * len(committee_indices)
    bits[0] = False
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices),
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block,
                                             expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_infinite_signature_with_all_participants(
        spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=spec.BLSSignature(
            b"\xc0" + b"\x00" * 95),  # point at infinity
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block,
                                             expect_exception=True)


@with_altair_and_later
@spec_state_test
@always_bls
def test_invalid_signature_infinite_signature_with_single_participant(
        spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    bits = [False] * len(committee_indices)
    bits[0] = True
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=spec.BLSSignature(
            b"\xc0" + b"\x00" * 95),
    )
    spec.process_slots(state, block.slot)
    yield from run_sync_committee_processing(spec, state, block,
                                             expect_exception=True)


@with_altair_and_later
@spec_state_test
def test_sync_committee_rewards_nonduplicate_committee(spec, state):
    # proposer reward accounting: proposer earns a cut per participant
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices),
    )
    spec.process_slots(state, block.slot)
    proposer = spec.get_beacon_proposer_index(state)
    pre_proposer = int(state.balances[proposer])
    yield from run_sync_committee_processing(spec, state, block)
    assert int(state.balances[proposer]) > pre_proposer


@with_altair_and_later
@spec_state_test
def test_sync_committee_rewards_empty_participants(spec, state):
    # no participants: every committee member is penalized, none rewarded
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[False] * len(committee_indices),
        sync_committee_signature=spec.G2_POINT_AT_INFINITY
        if hasattr(spec, "G2_POINT_AT_INFINITY")
        else spec.BLSSignature(b"\xc0" + b"\x00" * 95),
    )
    spec.process_slots(state, block.slot)
    pre = [int(state.balances[i]) for i in committee_indices]
    yield from run_sync_committee_processing(spec, state, block)
    post = [int(state.balances[i]) for i in committee_indices]
    assert all(b <= a for a, b in zip(pre, post))


@with_altair_and_later
@spec_state_test
def test_sync_committee_rewards_duplicate_committee_members(spec, state):
    # minimal registries repeat members in the sync committee: rewards
    # accrue once PER SLOT in the committee, not once per validator
    committee = state.current_sync_committee.pubkeys
    committee_indices = compute_committee_indices(state)
    duplicated = len(committee) != len(set(bytes(p) for p in committee))
    if not duplicated:
        # registry large enough that no duplicates occur — nothing to test
        return
    block = build_empty_block_for_next_slot(spec, state)
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block.slot - 1, committee_indices),
    )
    spec.process_slots(state, block.slot)
    from collections import Counter
    multiplicity = Counter(committee_indices)
    index, count = multiplicity.most_common(1)[0]
    assert count >= 2
    single_index = min(i for i in committee_indices
                       if multiplicity[i] == 1) \
        if any(multiplicity[i] == 1 for i in committee_indices) else None
    pre = int(state.balances[index])
    pre_single = int(state.balances[single_index]) \
        if single_index is not None else None
    proposer = spec.get_beacon_proposer_index(state)
    yield from run_sync_committee_processing(spec, state, block)
    gain = int(state.balances[index]) - pre
    if single_index is not None and single_index != proposer \
            and index != proposer:
        single_gain = int(state.balances[single_index]) - pre_single
        assert gain == count * single_gain


@with_altair_and_later
@spec_state_test
def test_proposer_in_committee_with_participation(spec, state):
    committee_indices = compute_committee_indices(state)
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    proposer = spec.get_beacon_proposer_index(state)
    if proposer not in committee_indices:
        return  # committee draw excluded the proposer this slot
    state_copy = state.copy()
    block.body.sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=[True] * len(committee_indices),
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state_copy, block.slot - 1, committee_indices),
    )
    pre = int(state.balances[proposer])
    yield from run_sync_committee_processing(spec, state, block)
    # proposer earns both the participant reward and the proposer cut
    assert int(state.balances[proposer]) > pre
