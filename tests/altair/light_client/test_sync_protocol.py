"""Altair light-client sync-protocol tests.

Reference model: ``test/altair/light_client/test_sync.py`` +
``test_update_ranking.py`` against
``specs/altair/light-client/sync-protocol.md``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_config_overrides, always_bls,
    never_bls, pytest_only, expect_assertion_error,
)

# light-client derivation requires the altair fork to be active at genesis
# (full-node.md asserts epoch >= ALTAIR_FORK_EPOCH; default configs pin
# fork epochs to FAR_FUTURE like the reference's)
altair_active = with_config_overrides({"ALTAIR_FORK_EPOCH": 0})
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.sync_committee import (
    compute_aggregate_sync_committee_signature, compute_committee_indices,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root, compute_merkle_proof


def _advance_chain(spec, state, n_blocks):
    """Apply n empty blocks; returns [(signed_block, post_state_copy)]."""
    out = []
    for _ in range(n_blocks):
        block = build_empty_block_for_next_slot(spec, state)
        signed = state_transition_and_sign_block(spec, state, block)
        out.append((signed, state.copy()))
    return out


def _signed_sync_aggregate(spec, signing_state, attested_root, signature_slot,
                           participation=1.0):
    committee_indices = compute_committee_indices(signing_state)
    n = int(len(committee_indices) * participation)
    participants = committee_indices[:n]
    bits = [i < n for i in range(len(committee_indices))]
    signature = compute_aggregate_sync_committee_signature(
        spec, signing_state, signature_slot - 1, participants,
        block_root=attested_root)
    return spec.SyncAggregate(sync_committee_bits=bits,
                              sync_committee_signature=signature)


def _bootstrap_store(spec, chain):
    signed_block, post_state = chain[0]
    bootstrap = spec.create_light_client_bootstrap(post_state, signed_block)
    trusted_root = hash_tree_root(signed_block.message)
    return spec.initialize_light_client_store(trusted_root, bootstrap)


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
def test_bootstrap_proof_and_store_init(spec, state):
    chain = _advance_chain(spec, state, 1)
    store = _bootstrap_store(spec, chain)
    signed_block, post_state = chain[0]
    assert store.finalized_header.beacon.slot == signed_block.message.slot
    assert store.current_sync_committee == post_state.current_sync_committee
    assert not spec.is_next_sync_committee_known(store)
    # tampered branch must be rejected
    bad = spec.create_light_client_bootstrap(post_state, signed_block)
    bad.current_sync_committee_branch[0] = b"\x13" * 32
    try:
        spec.initialize_light_client_store(
            hash_tree_root(signed_block.message), bad)
        raise SystemExit("tampered bootstrap must fail")
    except AssertionError:
        pass


@with_phases(["altair"])
@altair_active
@spec_state_test
@always_bls
def test_process_light_client_update_optimistic(spec, state):
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]

    attested_header = spec.block_to_light_client_header(attested_block)
    signature_slot = attested_block.message.slot + 1
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, hash_tree_root(attested_block.message),
        signature_slot)
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    current_slot = signature_slot
    spec.process_light_client_update(
        store, update, current_slot, attested_state.genesis_validators_root)

    # optimistic header advanced; no finality -> finalized unchanged
    assert store.optimistic_header.beacon.slot == attested_block.message.slot
    assert store.finalized_header.beacon.slot == chain[0][0].message.slot
    assert store.best_valid_update == update
    assert store.current_max_active_participants == \
        spec.SYNC_COMMITTEE_SIZE


@with_phases(["altair"])
@altair_active
@spec_state_test
@always_bls
def test_invalid_signature_rejected(spec, state):
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]
    signature_slot = attested_block.message.slot + 1
    # sign the WRONG root
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, spec.Root(b"\x66" * 32), signature_slot)
    update = spec.LightClientUpdate(
        attested_header=spec.block_to_light_client_header(attested_block),
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    try:
        spec.process_light_client_update(
            store, update, signature_slot,
            attested_state.genesis_validators_root)
        raise SystemExit("invalid signature must fail")
    except AssertionError:
        pass


@with_phases(["altair"])
@altair_active
@spec_state_test
@always_bls
def test_finality_branch_genesis_case(spec, state):
    """Finality update whose finalized checkpoint is still the genesis
    zero-root (sync-protocol.md:361 special case)."""
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]
    assert bytes(attested_state.finalized_checkpoint.root) == b"\x00" * 32

    signature_slot = attested_block.message.slot + 1
    update = spec.LightClientUpdate(
        attested_header=spec.block_to_light_client_header(attested_block),
        finality_branch=compute_merkle_proof(
            attested_state, spec.FINALIZED_ROOT_GINDEX),
        sync_aggregate=_signed_sync_aggregate(
            spec, attested_state, hash_tree_root(attested_block.message),
            signature_slot),
        signature_slot=signature_slot,
    )
    assert spec.is_finality_update(update)
    spec.validate_light_client_update(
        store, update, signature_slot,
        attested_state.genesis_validators_root)


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
def test_is_better_update_ranking(spec, state):
    def mk(participation_n, attested_slot, signature_slot):
        bits = [i < participation_n for i in range(spec.SYNC_COMMITTEE_SIZE)]
        return spec.LightClientUpdate(
            attested_header=spec.LightClientHeader(
                beacon=spec.BeaconBlockHeader(slot=attested_slot)),
            sync_aggregate=spec.SyncAggregate(sync_committee_bits=bits),
            signature_slot=signature_slot,
        )

    n = spec.SYNC_COMMITTEE_SIZE
    # supermajority beats non-supermajority
    assert spec.is_better_update(mk(n, 10, 11), mk(n // 2, 10, 11))
    # higher participation wins below supermajority
    assert spec.is_better_update(mk(n // 2, 10, 11), mk(n // 3, 10, 11))
    # both supermajority: higher participation wins
    assert spec.is_better_update(mk(n, 10, 11), mk((2 * n + 2) // 3, 10, 11))
    # tie on participation: older attested data wins
    assert spec.is_better_update(mk(n, 9, 11), mk(n, 10, 11))
    assert not spec.is_better_update(mk(n, 10, 11), mk(n, 9, 11))


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
def test_force_update_after_timeout(spec, state):
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, _ = chain[1]
    bits = [True] * spec.SYNC_COMMITTEE_SIZE
    store.best_valid_update = spec.LightClientUpdate(
        attested_header=spec.block_to_light_client_header(attested_block),
        sync_aggregate=spec.SyncAggregate(sync_committee_bits=bits),
        signature_slot=attested_block.message.slot + 1,
    )
    timeout_slot = store.finalized_header.beacon.slot + \
        spec.UPDATE_TIMEOUT + 1
    spec.process_light_client_store_force_update(store, timeout_slot)
    # forced update promotes attested header to finalized
    assert store.finalized_header.beacon.slot == attested_block.message.slot
    assert store.best_valid_update is None


@with_phases(["capella", "deneb"])
@with_config_overrides({"ALTAIR_FORK_EPOCH": 0, "BELLATRIX_FORK_EPOCH": 0,
                        "CAPELLA_FORK_EPOCH": 0, "DENEB_FORK_EPOCH": 0})
@spec_state_test
@never_bls
def test_capella_header_execution_branch_roundtrip(spec, state):
    """Capella+ LightClientHeader carries the execution header proven
    into the block body (capella/light-client/sync-protocol.md:48-88)."""
    chain = _advance_chain(spec, state, 1)
    signed_block, _ = chain[0]
    header = spec.block_to_light_client_header(signed_block)
    assert header.execution.block_hash == \
        signed_block.message.body.execution_payload.block_hash
    assert spec.is_valid_light_client_header(header)
    # tampering with the execution header breaks the branch
    bad = header.copy()
    bad.execution.gas_used = 999
    assert not spec.is_valid_light_client_header(bad)


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
@pytest_only
def test_bootstrap_wrong_trusted_root_rejected(spec, state):
    chain = _advance_chain(spec, state, 1)
    signed_block, post_state = chain[0]
    bootstrap = spec.create_light_client_bootstrap(post_state, signed_block)
    expect_assertion_error(
        lambda: spec.initialize_light_client_store(b"\x13" * 32, bootstrap))
    yield


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
@pytest_only
def test_insufficient_participation_rejected(spec, state):
    """An update whose aggregate carries fewer than
    MIN_SYNC_COMMITTEE_PARTICIPANTS bits is invalid
    (sync-protocol.md validate_light_client_update)."""
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]
    attested_header = spec.block_to_light_client_header(attested_block)
    signature_slot = attested_block.message.slot + 1
    floor = spec.MIN_SYNC_COMMITTEE_PARTICIPANTS
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, hash_tree_root(attested_block.message),
        signature_slot,
        participation=(floor - 1) / spec.SYNC_COMMITTEE_SIZE)
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    current_slot = int(signature_slot)
    expect_assertion_error(
        lambda: spec.process_light_client_update(
            store, update, current_slot,
            attested_state.genesis_validators_root))
    yield


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
@pytest_only
def test_sub_supermajority_update_does_not_finalize(spec, state):
    """At 50% participation an update is collected (best_valid_update)
    and the optimistic header advances past the safety threshold — but
    without a 2/3 supermajority (and no finality proof) the FINALIZED
    header must not move (sync-protocol.md
    process_light_client_update apply conditions)."""
    chain = _advance_chain(spec, state, 2)
    store = _bootstrap_store(spec, chain)
    attested_block, attested_state = chain[1]
    attested_header = spec.block_to_light_client_header(attested_block)
    signature_slot = attested_block.message.slot + 1
    sync_aggregate = _signed_sync_aggregate(
        spec, attested_state, hash_tree_root(attested_block.message),
        signature_slot, participation=0.5)  # >= floor, < 2/3
    update = spec.LightClientUpdate(
        attested_header=attested_header,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot,
    )
    pre_finalized_slot = int(store.finalized_header.beacon.slot)
    current_slot = int(signature_slot)
    spec.process_light_client_update(
        store, update, current_slot, attested_state.genesis_validators_root)
    assert store.best_valid_update is not None
    # optimistic header advances (participation > safety threshold) ...
    assert int(store.optimistic_header.beacon.slot) == \
        int(attested_block.message.slot)
    # ... but the finalized header does not (no supermajority, no proof)
    assert int(store.finalized_header.beacon.slot) == pre_finalized_slot
