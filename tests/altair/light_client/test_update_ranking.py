"""``is_better_update`` total-order coverage.

Reference model:
``test/altair/light_client/test_update_ranking.py`` (construct updates
differing in one ranking criterion each, assert the full sort order)
against ``specs/altair/light-client/sync-protocol.md``
``is_better_update``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_config_overrides, never_bls,
)

altair_active = with_config_overrides({"ALTAIR_FORK_EPOCH": 0})


def _aggregate(spec, num_participants):
    size = int(spec.SYNC_COMMITTEE_SIZE)
    return spec.SyncAggregate(
        sync_committee_bits=[i < num_participants for i in range(size)],
        sync_committee_signature=spec.BLSSignature(b"\x11" * 96),
    )


def _update(spec, state, participants, with_committee=False,
            with_finality=False, attested_slot=1, signature_slot=2):
    """A synthetic update; branches are nonzero markers (ranking only
    inspects emptiness/periods, not proof validity)."""
    update = spec.LightClientUpdate(
        sync_aggregate=_aggregate(spec, participants),
        signature_slot=signature_slot,
    )
    update.attested_header.beacon.slot = attested_slot
    if with_committee:
        update.next_sync_committee_branch = type(
            update.next_sync_committee_branch)(
                [b"\x22" * 32
                 for _ in range(len(update.next_sync_committee_branch))])
    if with_finality:
        update.finality_branch = type(update.finality_branch)(
            [b"\x33" * 32 for _ in range(len(update.finality_branch))])
        update.finalized_header.beacon.slot = max(0, attested_slot - 8)
    return update


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
def test_update_ranking(spec, state):
    size = int(spec.SYNC_COMMITTEE_SIZE)
    supermajority = size * 2 // 3 + 1
    minority = size // 3
    # best -> worst, one ranking rule apart each step
    ranked = [
        # supermajority + relevant committee + finality
        _update(spec, state, size, with_committee=True, with_finality=True),
        # same but fewer (still supermajority) participants
        _update(spec, state, supermajority, with_committee=True,
                with_finality=True),
        # supermajority + committee, no finality
        _update(spec, state, supermajority, with_committee=True),
        # supermajority only
        _update(spec, state, supermajority),
        # sub-supermajority: more participants beat fewer
        _update(spec, state, minority, with_committee=True,
                with_finality=True),
        _update(spec, state, minority - 1, with_committee=True,
                with_finality=True),
    ]
    for i, high in enumerate(ranked):
        for low in ranked[i + 1:]:
            assert spec.is_better_update(high, low)
            assert not spec.is_better_update(low, high)
    yield


@with_phases(["altair"])
@altair_active
@spec_state_test
@never_bls
def test_update_ranking_tiebreakers(spec, state):
    """Equal on all class rules: earlier attested slot, then earlier
    signature slot, wins."""
    size = int(spec.SYNC_COMMITTEE_SIZE)
    older = _update(spec, state, size, attested_slot=1, signature_slot=3)
    newer = _update(spec, state, size, attested_slot=2, signature_slot=3)
    assert spec.is_better_update(older, newer)
    assert not spec.is_better_update(newer, older)

    early_sig = _update(spec, state, size, attested_slot=2,
                        signature_slot=3)
    late_sig = _update(spec, state, size, attested_slot=2,
                       signature_slot=4)
    assert spec.is_better_update(early_sig, late_sig)
    assert not spec.is_better_update(late_sig, early_sig)
    yield
