"""Single-merkle-proof vectors for light-client gindices.

Reference model:
``test/altair/light_client/test_single_merkle_proof.py`` (proofs for
current/next sync committee and finalized root out of a BeaconState)
against ``specs/altair/light-client/sync-protocol.md`` constants +
``ssz/merkle-proofs.md``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_all_phases_from,
)
from consensus_specs_tpu.utils.ssz import (
    hash_tree_root, compute_merkle_proof,
)

with_altair_and_later = with_all_phases_from("altair")


def _run_state_proof_test(spec, state, gindex, leaf_root):
    from consensus_specs_tpu.forks.light_client import floorlog2
    proof = compute_merkle_proof(state, gindex)
    yield "object", state
    yield "proof", {
        "leaf": "0x" + bytes(leaf_root).hex(),
        "leaf_index": int(gindex),
        "branch": ["0x" + bytes(b).hex() for b in proof],
    }
    assert len(proof) == floorlog2(gindex)
    assert spec.is_valid_merkle_branch(
        leaf=leaf_root, branch=proof, depth=floorlog2(gindex),
        index=spec.get_subtree_index(gindex), root=hash_tree_root(state))
    # a flipped sibling must break verification
    bad = list(proof)
    bad[0] = spec.Bytes32(bytes(32))
    if bad[0] == proof[0]:
        bad[0] = spec.Bytes32(b"\x01" * 32)
    assert not spec.is_valid_merkle_branch(
        leaf=leaf_root, branch=bad, depth=floorlog2(gindex),
        index=spec.get_subtree_index(gindex), root=hash_tree_root(state))


@with_altair_and_later
@spec_state_test
def test_current_sync_committee_merkle_proof(spec, state):
    yield from _run_state_proof_test(
        spec, state, spec.CURRENT_SYNC_COMMITTEE_GINDEX,
        hash_tree_root(state.current_sync_committee))


@with_altair_and_later
@spec_state_test
def test_next_sync_committee_merkle_proof(spec, state):
    yield from _run_state_proof_test(
        spec, state, spec.NEXT_SYNC_COMMITTEE_GINDEX,
        hash_tree_root(state.next_sync_committee))


@with_altair_and_later
@spec_state_test
def test_finality_root_merkle_proof(spec, state):
    yield from _run_state_proof_test(
        spec, state, spec.FINALIZED_ROOT_GINDEX,
        hash_tree_root(state.finalized_checkpoint.root))
