"""upgrade_to_altair fork tests.

Reference model: ``test/altair/fork/test_altair_fork_basic.py`` -
build a phase0 state, upgrade, check invariants.
"""
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.utils.ssz import hash_tree_root


def run_fork_test(post_spec, pre_state):
    yield "pre", pre_state
    post_state = post_spec.upgrade_to_altair(pre_state)

    # stable fields stay identical
    for field in ("genesis_time", "genesis_validators_root", "slot",
                  "eth1_deposit_index", "justification_bits"):
        assert getattr(pre_state, field) == getattr(post_state, field)
    for field in ("block_roots", "state_roots", "historical_roots",
                  "validators", "balances", "randao_mixes", "slashings"):
        assert hash_tree_root(getattr(pre_state, field)) == \
            hash_tree_root(getattr(post_state, field))

    # fork versions
    assert post_state.fork.previous_version == pre_state.fork.current_version
    assert bytes(post_state.fork.current_version) == \
        bytes(post_spec.config.ALTAIR_FORK_VERSION)

    # new fields sized to the registry
    assert len(post_state.previous_epoch_participation) == \
        len(post_state.validators)
    assert len(post_state.current_epoch_participation) == \
        len(post_state.validators)
    assert len(post_state.inactivity_scores) == len(post_state.validators)
    assert all(int(s) == 0 for s in post_state.inactivity_scores)

    # sync committees populated
    assert len(post_state.current_sync_committee.pubkeys) == \
        post_spec.SYNC_COMMITTEE_SIZE
    yield "post", post_state


@with_phases(["phase0"])
@spec_state_test
@never_bls
def test_altair_fork_basic(spec, state):
    post_spec = build_spec("altair", spec.preset_name)
    yield from run_fork_test(post_spec, state)


@with_phases(["phase0"])
@spec_state_test
@never_bls
def test_altair_fork_next_epoch(spec, state):
    next_epoch(spec, state)
    post_spec = build_spec("altair", spec.preset_name)
    yield from run_fork_test(post_spec, state)
