"""Durable-replay suite: crash-consistent checkpoint/restore, the
write-ahead journal, the recovery ladder, the recovery.* engine-site
contract, and the kill/restart + corruption harness legs
(``consensus_specs_tpu/recovery/`` + ``sim/recovery.py``;
docs/recovery.md).

Contracts under test:

* **atomicity** — every persisted file lands via temp + fsync + rename;
  a failed write never touches the final path;
* **journal integrity** — records CRC-validate, a torn tail and
  mid-file damage classify differently, uncommitted step events are
  discarded;
* **checkpoint integrity** — the manifest is the commit point, blob
  hashes gate every load, checkpointing is REFUSED inside an open
  ``arrays.commit_scope``;
* **recovery ladder** — every corruption case (truncated checkpoint
  blob, bit-flipped blob, truncated manifest, torn journal record,
  per-blob bit flips) is detected, counted on
  ``recovery.fallbacks{reason=}``, degrades to the previous generation
  and still produces the byte-identical digest — zero silent wrong
  resumes;
* **site contract** — ``recovery.checkpoint`` / ``recovery.restore``
  take injected faults as counted fallbacks, demote under a
  threshold-1 breaker, and rate-1 sentinel audits quarantine
  corrupt-mode results (the PR-9 contract at the new sites);
* **kill/restart** — a REAL SIGKILL mid-replay, restored from disk by
  a second process, byte-identical to the uninterrupted replay;
* **satellites** — the genesis cache keys by stable spec identity (the
  D1004 stale-aliasing fix) and a truncated repro artifact fails
  loudly.
"""
import json
import os

import pytest

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.recovery import atomic, journal
from consensus_specs_tpu.recovery.checkpoint import (
    CheckpointRefused, CheckpointStore, store_digest)
from consensus_specs_tpu.recovery.replay import DurableReplay
from consensus_specs_tpu.sim import driver, harness, scenarios
from consensus_specs_tpu.sim import recovery as rec_legs
from consensus_specs_tpu.state import arrays
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import bls

SEED = 2            # steady scenario: fast, finalizing
FORK_SEED = 1       # equivocation scenario: sibling forks in the tail
EVERY = 8


@pytest.fixture(autouse=True)
def _stub_bls(monkeypatch):
    # signatures off (digest covers everything but sig bytes), and the
    # subsystem under test FORCED on — the CS_TPU_CHECKPOINT=0 CI leg
    # re-runs this suite to prove the live switch overrides the job
    # env, exactly the mesh-suite convention (the off legs proper are
    # test_checkpoint_off_leg / the sim suite's default paths)
    monkeypatch.setenv("CS_TPU_CHECKPOINT", "1")
    prev = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev


@pytest.fixture(scope="module")
def ctx():
    """(spec, scenario, baseline digest) shared by the replay tests."""
    bls_prev = bls.bls_active
    bls.bls_active = False
    spec = build_spec("phase0", "minimal")
    epoch = int(spec.SLOTS_PER_EPOCH)
    scenario = scenarios.build(SEED, epoch, epoch * 8)
    try:
        with harness.env_overrides(harness.NEUTRAL_SUPERVISOR_ENV):
            baseline, _ = harness.run_baseline(spec, scenario)
    finally:
        bls.bls_active = bls_prev
    return spec, scenario, baseline


def _neutral(monkeypatch):
    for k, v in harness.NEUTRAL_SUPERVISOR_ENV.items():
        monkeypatch.setenv(k, v)
    supervisor.reset()


# ---------------------------------------------------------------------------
# atomic writes
# ---------------------------------------------------------------------------

def test_atomic_write_roundtrip_and_overwrite(tmp_path):
    path = str(tmp_path / "blob.bin")
    atomic.atomic_write_bytes(path, b"first")
    assert open(path, "rb").read() == b"first"
    atomic.atomic_write_bytes(path, b"second")
    assert open(path, "rb").read() == b"second"
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


def test_atomic_write_failure_never_touches_final_path(tmp_path,
                                                       monkeypatch):
    path = str(tmp_path / "blob.bin")
    atomic.atomic_write_bytes(path, b"old content")

    def boom(src, dst):
        raise OSError("disk pulled")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        atomic.atomic_write_bytes(path, b"half-writ")
    assert open(path, "rb").read() == b"old content"
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


# ---------------------------------------------------------------------------
# write-ahead journal
# ---------------------------------------------------------------------------

def _journal_with(tmp_path, *records):
    path = str(tmp_path / "wal.log")
    j = journal.Journal(path)
    for kind, payload in records:
        j.append(kind, payload)
    j.close()
    return path


def test_journal_roundtrip(tmp_path):
    path = _journal_with(tmp_path, (journal.TICK, b"\x01" * 8),
                         (journal.BLOCK, b"block bytes"))
    records, anomaly = journal.scan(path)
    assert anomaly is None
    assert records == [(journal.TICK, b"\x01" * 8),
                       (journal.BLOCK, b"block bytes")]


def test_journal_torn_tail_detected(tmp_path):
    path = _journal_with(tmp_path, (journal.TICK, b"\x02" * 8))
    with open(path, "ab") as f:
        f.write(journal.frame(journal.BLOCK, b"x" * 64)[:20])
    records, anomaly = journal.scan(path)
    assert anomaly == "torn"
    assert records == [(journal.TICK, b"\x02" * 8)]


def test_journal_midfile_corruption_detected(tmp_path):
    path = _journal_with(tmp_path, (journal.BLOCK, b"a" * 64),
                         (journal.BLOCK, b"b" * 64))
    with open(path, "r+b") as f:
        f.seek(12)      # inside the first record's payload
        f.write(b"\xff")
    records, anomaly = journal.scan(path)
    assert anomaly == "corrupt"
    assert records == []


def test_completed_steps_discards_uncommitted_tail(tmp_path):
    path = _journal_with(
        tmp_path,
        (journal.TICK, b"\x01" * 8),
        (journal.STEP, journal.step_payload(0, {"op": "tick"})),
        (journal.BLOCK, b"uncommitted"))
    records, anomaly = journal.scan(path)
    assert anomaly is None
    steps = journal.completed_steps(records)
    assert len(steps) == 1
    ordinal, step, events = steps[0]
    assert (ordinal, step) == (0, {"op": "tick"})
    assert events == [(journal.TICK, b"\x01" * 8)]


# ---------------------------------------------------------------------------
# checkpoint store
# ---------------------------------------------------------------------------

def _partial(spec, scenario, work, stop_at=None, every=EVERY):
    replay = DurableReplay(spec, scenario, str(work),
                           checkpoint_every=every)
    if stop_at is None:
        stop_at = rec_legs.pick_kill_step(scenario, every)
    replay.run(stop_at=stop_at)
    return replay.cs, stop_at


def test_checkpoint_save_load_roundtrip(ctx, tmp_path, monkeypatch):
    spec, scenario, _ = ctx
    _neutral(monkeypatch)
    cs, _ = _partial(spec, scenario, tmp_path / "ck")
    gens = cs.generations()
    assert len(gens) >= 2
    sim, step, manifest = cs.load(spec, gens[-1])
    # the restored store answers the same digest the manifest recorded
    assert store_digest(spec, sim.store) == manifest["digest"]
    assert step == manifest["step"]
    # sidecar state round-trips exactly
    sim2, _, _ = cs.load(spec, gens[-1])
    assert sim.snapshot_sidecar() == sim2.snapshot_sidecar()


def test_checkpoint_refused_inside_open_commit_scope(ctx, tmp_path,
                                                     monkeypatch):
    spec, scenario, _ = ctx
    _neutral(monkeypatch)
    arrays.use_arrays()
    try:
        sim = driver.ChainSim(spec, scenario.n_validators)
        sim.run(scenario.script[:6])
        cs = CheckpointStore(str(tmp_path / "ck"))
        head = bytes(spec.get_head(sim.store))
        state = sim.store.block_states[head]
        with arrays.commit_scope(state):
            # poke a deferred write so the scope is genuinely open
            sa = arrays.of(state)
            sa.set_balances(sa.balances().copy())
            with pytest.raises(CheckpointRefused):
                cs.save(spec, sim, 6)
        # scope closed: the same save goes through
        assert cs.save(spec, sim, 6) is not None
    finally:
        arrays.use_auto()


def test_manifest_is_the_commit_point(ctx, tmp_path, monkeypatch):
    spec, scenario, _ = ctx
    _neutral(monkeypatch)
    cs, _ = _partial(spec, scenario, tmp_path / "ck")
    newest = cs.generations()[-1]
    os.unlink(cs.manifest_path(newest))
    # blobs of the un-manifested generation still on disk, yet the
    # generation does not exist for recovery
    assert newest not in cs.generations()


def test_prune_keeps_newest_generations(ctx, tmp_path, monkeypatch):
    spec, scenario, _ = ctx
    _neutral(monkeypatch)
    cs, _ = _partial(spec, scenario, tmp_path / "ck")
    gens = cs.generations()
    assert len(gens) <= cs.keep
    assert gens == sorted(gens)


# ---------------------------------------------------------------------------
# recovery ladder: crash + resume, corruption matrix
# ---------------------------------------------------------------------------

def test_resume_after_boundary_crash_byte_identical(ctx, tmp_path,
                                                    monkeypatch):
    spec, scenario, baseline = ctx
    _neutral(monkeypatch)
    work = str(tmp_path / "ck")
    _partial(spec, scenario, work)
    with counting() as delta:
        result, info = DurableReplay(spec, scenario, work,
                                     checkpoint_every=EVERY).resume()
    assert result.digest() == baseline.digest()
    assert info["path"] == "checkpoint"
    assert delta["recovery.restores{path=checkpoint}"] == 1
    # each replayed step re-proves its events + its commit marker
    assert delta["recovery.journal.records{op=replayed}"] \
        >= info["journal_steps"]


def test_resume_replays_journal_tail(ctx, tmp_path, monkeypatch):
    """The resume point must sit PAST the checkpoint step: the journal
    tail really advances the restored store."""
    spec, scenario, baseline = ctx
    _neutral(monkeypatch)
    work = str(tmp_path / "ck")
    # stop at a step that is NOT a checkpoint boundary so a tail exists
    stop_at = rec_legs.pick_kill_step(scenario, EVERY)
    if stop_at % EVERY == 0:
        stop_at += 1
    _partial(spec, scenario, work, stop_at=stop_at)
    result, info = DurableReplay(spec, scenario, work,
                                 checkpoint_every=EVERY).resume()
    assert result.digest() == baseline.digest()
    assert info["path"] == "checkpoint"
    assert info["journal_steps"] == stop_at % EVERY


def test_journal_replay_across_fork_boundary(monkeypatch, tmp_path):
    """Resume with sibling forks, withheld blocks and queued evidence
    inside the journaled tail (the equivocation scenario) — the
    sidecar + journal replay must reconstruct the mid-fork driver."""
    spec = build_spec("phase0", "minimal")
    epoch = int(spec.SLOTS_PER_EPOCH)
    scenario = scenarios.build(FORK_SEED, epoch, epoch * 8)
    assert scenario.name == "equivocation"
    _neutral(monkeypatch)
    baseline = driver.execute(spec, scenario.script,
                              scenario.n_validators)
    work = str(tmp_path / "ck")
    # small cadence: the tail crosses the sibling-fork steps
    cs, stop_at = _partial(spec, scenario, work, every=4)
    result, info = DurableReplay(spec, scenario, work,
                                 checkpoint_every=4).resume()
    assert result.digest() == baseline.digest()
    assert info["path"] == "checkpoint"


def test_corruption_matrix(ctx, tmp_path):
    """truncated checkpoint blob / bit-flipped blob / truncated
    manifest / torn journal record: all detected, counted, degraded,
    byte-identical (the sweep leg, run directly)."""
    spec, scenario, baseline = ctx
    cases = rec_legs.run_corruption_matrix(spec, scenario, baseline,
                                           str(tmp_path))
    assert cases == {"truncated_state_blob": "blob",
                     "bitflip_block_blob": "blob",
                     "truncated_manifest": "manifest",
                     "torn_journal_record": "torn_record"}


@pytest.mark.parametrize("blob", ["blocks.bin", "states.bin",
                                  "ckpt_states.bin", "store_meta.json",
                                  "sidecar.json"])
def test_bitflip_each_blob_detected(ctx, tmp_path, monkeypatch, blob):
    """Per-blob corruption matrix: a single flipped bit in ANY
    manifest-hashed blob fails the generation and degrades."""
    spec, scenario, baseline = ctx
    _neutral(monkeypatch)
    work = str(tmp_path / "ck")
    cs, _ = _partial(spec, scenario, work)
    newest = cs.generations()[-1]
    path = cs.blob_path(newest, blob)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0x01
    open(path, "wb").write(bytes(data))
    with counting() as delta:
        result, info = DurableReplay(spec, scenario, work,
                                     checkpoint_every=EVERY).resume()
    assert delta["recovery.fallbacks{reason=blob}"] >= 1
    assert not (info["path"] == "checkpoint"
                and info["generation"] == newest)
    assert result.digest() == baseline.digest()


def test_wrong_scenario_checkpoint_dir_refused(ctx, tmp_path,
                                               monkeypatch):
    """A resume pointed at ANOTHER scenario's checkpoint directory —
    with an EMPTY journal tail, so no journaled step could catch it —
    must refuse every generation (counted) and fall to genesis
    re-execution of the RIGHT script, byte-identical."""
    spec, scenario, baseline = ctx
    _neutral(monkeypatch)
    epoch = int(spec.SLOTS_PER_EPOCH)
    other = scenarios.build(FORK_SEED, epoch, epoch * 8)
    assert other.name != scenario.name
    work = str(tmp_path / "ck")
    # stop exactly AT a checkpoint boundary: the newest generation's
    # journal is empty — the hole _replay_tail cannot cover
    replay = DurableReplay(spec, other, work, checkpoint_every=EVERY)
    replay.run(stop_at=2 * EVERY)
    with counting() as delta:
        result, info = DurableReplay(spec, scenario, work,
                                     checkpoint_every=EVERY).resume()
    assert info["path"] == "genesis"
    assert any(reason == "scenario_mismatch"
               for _, reason in info["rungs"])
    assert delta["recovery.fallbacks{reason=divergence}"] >= 1
    assert result.digest() == baseline.digest()


def test_midfile_journal_corruption_degrades(ctx, tmp_path, monkeypatch):
    spec, scenario, baseline = ctx
    _neutral(monkeypatch)
    work = str(tmp_path / "ck")
    cs, _ = _partial(spec, scenario, work)
    newest = cs.generations()[-1]
    wal = cs.journal_path(newest)
    data = bytearray(open(wal, "rb").read())
    if len(data) < 16:
        pytest.skip("journal tail too short to damage mid-file")
    data[10] ^= 0xff
    open(wal, "wb").write(bytes(data))
    with counting() as delta:
        result, info = DurableReplay(spec, scenario, work,
                                     checkpoint_every=EVERY).resume()
    assert delta["recovery.fallbacks{reason=journal_corrupt}"] \
        + delta["recovery.fallbacks{reason=torn_record}"] >= 1
    assert result.digest() == baseline.digest()


# ---------------------------------------------------------------------------
# recovery.* engine-site contract (breaker / injected / audit)
# ---------------------------------------------------------------------------

def test_injected_fault_at_checkpoint_site(ctx, tmp_path):
    spec, scenario, baseline = ctx
    rec_legs.run_recovery_injected(spec, scenario, baseline,
                                   str(tmp_path), "recovery.checkpoint")


def test_injected_fault_at_restore_site(ctx, tmp_path):
    spec, scenario, baseline = ctx
    rec_legs.run_recovery_injected(spec, scenario, baseline,
                                   str(tmp_path), "recovery.restore")


def test_breaker_demotes_checkpoint_site(ctx, tmp_path, monkeypatch):
    """Threshold-1 breaker: one injected checkpoint failure opens the
    site; later checkpoints SKIP (counted) and the replay finishes
    byte-identical with degraded durability."""
    spec, scenario, baseline = ctx
    for k, v in {"CS_TPU_BREAKER_THRESHOLD": "1",
                 "CS_TPU_BREAKER_WINDOW_MS": "60000",
                 "CS_TPU_BREAKER_BACKOFF_MS": "600000",
                 "CS_TPU_BREAKER_BACKOFF_MAX_MS": "600000"}.items():
        monkeypatch.setenv(k, v)
    supervisor.reset()
    schedule = faults.FaultSchedule({"recovery.checkpoint": [1]})
    with counting() as delta:
        with faults.injected(schedule):
            result = DurableReplay(spec, scenario, str(tmp_path / "ck"),
                                   checkpoint_every=4).run()
    assert schedule.fully_fired()
    assert delta["recovery.fallbacks{reason=injected}"] == 1
    assert delta["supervisor.transitions{site=recovery.checkpoint,"
                 "to=open}"] >= 1
    assert delta["supervisor.breaker.skips{site=recovery.checkpoint}"] \
        >= 1
    assert delta["recovery.checkpoints{result=skipped}"] >= 1
    assert result.digest() == baseline.digest()


def test_audit_quarantines_corrupt_checkpoint(ctx, tmp_path,
                                              monkeypatch):
    """Corrupt-mode checkpoint writes + rate-1 read-back audits: the
    first lying generation is caught, discarded and the site
    quarantined — durability degrades, the replay does not."""
    spec, scenario, baseline = ctx
    for k, v in harness.AUDIT_ENV.items():
        monkeypatch.setenv(k, v)
    supervisor.reset()
    schedule = faults.FaultSchedule(
        corrupt={"recovery.checkpoint": [1]})
    work = str(tmp_path / "ck")
    with counting() as delta:
        with faults.injected(schedule):
            result = DurableReplay(spec, scenario, work,
                                   checkpoint_every=4).run()
    assert schedule.corrupted, "corrupt hook never armed"
    assert delta["supervisor.audits{result=fail,"
                 "site=recovery.checkpoint}"] >= 1
    assert delta["supervisor.quarantines{site=recovery.checkpoint}"] == 1
    # the lying generation was discarded: whatever remains verifies
    cs = CheckpointStore(work)
    for gen in cs.generations():
        ok, detail = cs.verify(gen)
        assert ok, detail
    assert result.digest() == baseline.digest()


def test_audit_catches_corrupt_restore(ctx, tmp_path, monkeypatch):
    """Corrupt-mode restore + rate-1 digest audits: the silently-wrong
    restored store is caught against the manifest digest, the site
    quarantined, and the ladder degrades to genesis re-execution —
    byte-identical."""
    spec, scenario, baseline = ctx
    work = str(tmp_path / "ck")
    with harness.env_overrides(harness.NEUTRAL_SUPERVISOR_ENV):
        _partial(spec, scenario, work)
    for k, v in harness.AUDIT_ENV.items():
        monkeypatch.setenv(k, v)
    supervisor.reset()
    schedule = faults.FaultSchedule(corrupt={"recovery.restore": [1]})
    with counting() as delta:
        with faults.injected(schedule):
            result, info = DurableReplay(spec, scenario, work,
                                         checkpoint_every=EVERY).resume()
    assert schedule.corrupted, "corrupt hook never armed"
    assert delta["supervisor.audits{result=fail,"
                 "site=recovery.restore}"] >= 1
    assert delta["supervisor.quarantines{site=recovery.restore}"] == 1
    assert info["path"] == "genesis"
    assert result.digest() == baseline.digest()


# ---------------------------------------------------------------------------
# restored state + the columnar store (satellite: COW behavior)
# ---------------------------------------------------------------------------

def test_restore_then_fork_state_shares_columns(ctx, tmp_path,
                                                monkeypatch):
    """Restored states re-derive their StateArrays columns lazily, and
    ``fork_state`` of a restored state SHARES them copy-on-write (the
    committed cell data is the same array object, not a copy)."""
    spec, scenario, _ = ctx
    _neutral(monkeypatch)
    arrays.use_arrays()
    try:
        cs, _ = _partial(spec, scenario, tmp_path / "ck")
        sim, _, _ = cs.load(spec, cs.generations()[-1])
        head = bytes(spec.get_head(sim.store))
        state = sim.store.block_states[head]
        sa = arrays.of(state)
        parent_col = sa.registry()
        parent_bal = sa.balances()      # extracted BEFORE the fork:
        #                                 only attached cells ride along
        child = arrays.fork_state(state)
        child_sa = arrays.of(child)
        assert child_sa.registry() is parent_col
        assert child_sa.balances() is parent_bal
    finally:
        arrays.use_auto()


def test_restore_then_fork_single_replacement_under_mesh(ctx, tmp_path,
                                                         monkeypatch):
    """Under the mesh, a restored state's column is PLACED once and the
    copy-on-write fork rides the same placement: <= 1 registry
    placement across parent + child reads."""
    from consensus_specs_tpu.parallel import mesh_state
    if mesh_state.device_count() < 2:
        pytest.skip("needs a multi-device host")
    spec, scenario, _ = ctx
    _neutral(monkeypatch)
    arrays.use_arrays()
    mesh_state.use_mesh()
    try:
        cs, _ = _partial(spec, scenario, tmp_path / "ck")
        sim, _, _ = cs.load(spec, cs.generations()[-1])
        head = bytes(spec.get_head(sim.store))
        state = sim.store.block_states[head]
        sa = arrays.of(state)
        mesh = mesh_state.build_mesh()
        with counting() as delta:
            mesh_state.sharded_cell(sa, "registry", mesh)
            child = arrays.fork_state(state)
            mesh_state.sharded_cell(arrays.of(child), "registry", mesh)
        assert delta["mesh.placements{column=registry}"] == 1
    finally:
        mesh_state.use_auto()
        arrays.use_auto()


# ---------------------------------------------------------------------------
# harness legs: kill/restart (real SIGKILL), checkpoint-off
# ---------------------------------------------------------------------------

def test_kill_restart_subprocess_round_trip(ctx, tmp_path):
    """The acceptance leg: a subprocess replay SIGKILLed at a seeded
    step, restarted from checkpoint + journal, finishes byte-identical
    to the uninterrupted replay."""
    spec, scenario, baseline = ctx
    info = rec_legs.run_kill_restart(spec, scenario, baseline,
                                     str(tmp_path))
    assert info["path"] == "checkpoint"


def test_checkpoint_off_leg(ctx, tmp_path):
    spec, scenario, baseline = ctx
    rec_legs.run_checkpoint_off(spec, scenario, baseline, str(tmp_path))


# ---------------------------------------------------------------------------
# satellites: genesis-cache identity, truncated artifact
# ---------------------------------------------------------------------------

def test_genesis_cache_keys_by_spec_identity():
    """Regression for the id(spec) stale-aliasing bug: an EQUAL but
    DISTINCT spec instance (the shape a GC'd-and-reused id would fake)
    must HIT the cache entry, and different configs must not."""
    from consensus_specs_tpu.config import load_config, load_preset
    from consensus_specs_tpu.forks import fork_registry
    spec = build_spec("phase0", "minimal")
    other = fork_registry()["phase0"](load_preset("minimal"),
                                      load_config("minimal"),
                                      preset_name="minimal")
    assert other is not spec
    assert driver._spec_identity(other) == driver._spec_identity(spec)
    driver._GENESIS_CACHE.clear()
    driver.genesis_state(spec, 8)
    assert len(driver._GENESIS_CACHE) == 1
    driver.genesis_state(other, 8)      # equal identity: cache hit
    assert len(driver._GENESIS_CACHE) == 1
    altair = build_spec("altair", "minimal")
    assert driver._spec_identity(altair) != driver._spec_identity(spec)
    overridden = build_spec("phase0", "minimal",
                            {"SHARD_COMMITTEE_PERIOD": 2})
    assert driver._spec_identity(overridden) \
        != driver._spec_identity(spec)


def test_truncated_artifact_fails_loudly(tmp_path):
    """A torn repro artifact (only possible via an outside writer now
    that dump_artifact is atomic) must raise a loud, path-naming
    error, not a bare JSONDecodeError."""
    from consensus_specs_tpu.sim import repro
    path = str(tmp_path / "repro_truncated.json")
    with open(path, "w") as f:
        f.write('{"scenario": "steady", "seed": 1, "scr')
    with pytest.raises(ValueError) as err:
        repro.load_artifact(path)
    assert "repro_truncated.json" in str(err.value)
    assert "truncated or corrupted" in str(err.value)


def test_dump_artifact_is_atomic(tmp_path, monkeypatch):
    """dump_artifact writes through recovery/atomic.py: no .tmp
    leftovers, valid JSON at the final path."""
    from consensus_specs_tpu.sim import repro
    scenario = scenarios.Scenario("steady", 1, [{"op": "tick"}], 8)
    path = repro.dump_artifact(scenario, "unit", "msg",
                               out_dir=str(tmp_path))
    payload = json.load(open(path))
    assert payload["scenario"] == "steady"
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


# ---------------------------------------------------------------------------
# sidecar round-trip
# ---------------------------------------------------------------------------

def test_sidecar_roundtrip(ctx, monkeypatch):
    spec, scenario, _ = ctx
    _neutral(monkeypatch)
    sim = driver.ChainSim(spec, scenario.n_validators)
    sim.run(scenario.script[:20])
    snap = sim.snapshot_sidecar()
    other = driver.ChainSim.restored(spec, sim.store, sim.anchor_root)
    other.restore_sidecar(json.loads(json.dumps(snap)))
    assert other.snapshot_sidecar() == snap
    assert other.tips == sim.tips
    assert other.statuses == sim.statuses
