"""Dirty-subtree root caching: differential + incremental-tree tests.

The ownership/dirty protocol (``utils/ssz/types.py``) must keep every
cached root EXACTLY equal to a from-scratch recompute after arbitrary
API mutations — a stale cache is a consensus bug.  The oracle here is
``decode_bytes(serialize())``: a fresh value with no caches at all.
Reference role: remerkleable's backing-tree correctness
(``setup.py:549``).
"""
import random

from consensus_specs_tpu.utils.ssz.merkle import (
    IncrementalTree, merkleize_chunks, zero_hashes)
from consensus_specs_tpu.utils.ssz import (
    Bitlist, Bytes32, Container, List, Vector, uint64)


class Inner(Container):
    a: uint64
    b: Bytes32


class Outer(Container):
    nums: List[uint64, 4096]
    inners: List[Inner, 1024]
    fixed: Vector[Bytes32, 16]
    bits: Bitlist[64]
    tag: uint64


def _fresh_root(v):
    return type(v).decode_bytes(v.serialize()).hash_tree_root()


def test_incremental_tree_matches_merkleize():
    rng = random.Random(1)
    for count in (0, 1, 2, 3, 7, 8, 64, 65):
        chunks = [rng.randbytes(32) for _ in range(count)]
        t = IncrementalTree(chunks, 4096)
        assert t.root() == merkleize_chunks(chunks, limit=4096)
        # single-chunk updates track full recomputes
        for _ in range(5):
            if not chunks:
                break
            i = rng.randrange(len(chunks))
            chunks[i] = rng.randbytes(32)
            t.update({i: chunks[i]})
            assert t.root() == merkleize_chunks(chunks, limit=4096)
        # growth via update beyond the occupied prefix
        chunks.append(rng.randbytes(32))
        t.update({len(chunks) - 1: chunks[-1]})
        assert t.root() == merkleize_chunks(chunks, limit=4096)
        # truncation
        if len(chunks) > 1:
            chunks = chunks[: len(chunks) // 2]
            t.truncate(len(chunks))
            assert t.root() == merkleize_chunks(chunks, limit=4096)


def test_empty_tree_root_is_zero_subtree():
    t = IncrementalTree([], 4096)
    assert t.root() == zero_hashes[12]


def test_randomized_mutations_never_stale():
    rng = random.Random(42)
    v = Outer(
        nums=list(range(100)),
        inners=[Inner(a=i, b=bytes([i % 256]) * 32) for i in range(50)],
        bits=[True, False] * 10,
    )
    assert v.hash_tree_root() == _fresh_root(v)

    def mutate():
        op = rng.randrange(9)
        if op == 0:
            v.nums[rng.randrange(len(v.nums))] = rng.randrange(2**64)
        elif op == 1:
            v.nums.append(rng.randrange(2**64))
        elif op == 2 and len(v.nums) > 1:
            v.nums.pop()
        elif op == 3:
            v.inners[rng.randrange(len(v.inners))].a = rng.randrange(2**64)
        elif op == 4:
            v.inners[rng.randrange(len(v.inners))] = Inner(
                a=rng.randrange(2**64), b=rng.randbytes(32))
        elif op == 5:
            v.inners.append(Inner(a=rng.randrange(2**64)))
        elif op == 6:
            v.fixed[rng.randrange(16)] = rng.randbytes(32)
        elif op == 7:
            v.bits[rng.randrange(len(v.bits))] = rng.randrange(2)
        else:
            v.tag = rng.randrange(2**64)

    for step in range(300):
        mutate()
        if step % 3 == 0:   # roots queried at varying cadence: caches must
            # survive BOTH repeated queries and query-free mutation bursts
            assert v.hash_tree_root() == _fresh_root(v), f"stale at {step}"
    assert v.hash_tree_root() == _fresh_root(v)


def test_copies_are_independent():
    v = Outer(nums=[1, 2, 3], inners=[Inner(a=1)])
    r0 = v.hash_tree_root()
    c = v.copy()
    assert c.hash_tree_root() == r0
    # mutating the copy (incl. in-place element writes) leaves the
    # original untouched, and vice versa
    c.inners[0].a = 99
    c.nums[0] = 77
    assert v.hash_tree_root() == r0
    assert c.hash_tree_root() == _fresh_root(c) != r0
    v.inners[0].a = 5
    assert c.inners[0].a == 99
    assert v.hash_tree_root() == _fresh_root(v)


def test_aliased_element_mutation_after_copy():
    v = Outer(inners=[Inner(a=1), Inner(a=2)])
    held = v.inners[0]          # live reference into v
    c = v.copy()
    r_c = c.hash_tree_root()
    held.a = 123                # must dirty v, not c
    assert v.inners[0].a == 123
    assert v.hash_tree_root() == _fresh_root(v)
    assert c.hash_tree_root() == r_c
    assert c.inners[0].a == 1
