"""Differential tests: JAX BLS12-381 kernels vs the pure-python oracle.

Fast tier (always on): Fq limb arithmetic, Fq2/Fq12 towers, G1/G2 complete
point formulas - each jit compiles in seconds.

Heavy tier (set ``CS_TPU_HEAVY=1``): full pairing bilinearity and the
end-to-end ``bls.use_jax()`` backend - the pairing program takes minutes to
compile cold on the 1-core CI box (cached in ``.jax_cache`` afterwards).
"""
import random

import numpy as np
import pytest
import jax

from consensus_specs_tpu.ops.bls12_381.fields import P, R_ORDER, Fq2, Fq6, Fq12
from consensus_specs_tpu.ops.bls12_381.curve import (
    G1_GENERATOR, G2_GENERATOR, G1Point)
from consensus_specs_tpu.ops.jax_bls import limbs as L
from consensus_specs_tpu.ops.jax_bls import tower as T
from consensus_specs_tpu.ops.jax_bls import points as PT

from consensus_specs_tpu.utils.env_flags import HEAVY  # noqa: E402
rng = random.Random(1234)


def rand_fq():
    return rng.randrange(P)


def rand_fq2():
    return Fq2(rand_fq(), rand_fq())


def test_limb_roundtrip():
    for v in (0, 1, P - 1, rand_fq()):
        assert L.limbs_to_int(L.int_to_limbs(v)) == v


def test_limb_field_ops_match_python():
    xs = [rand_fq() for _ in range(6)] + [0, 1, P - 1]
    ys = [rand_fq() for _ in range(6)] + [P - 1, 0, 1]
    A, B = L.pack_ints_mont(xs), L.pack_ints_mont(ys)
    assert L.unpack_mont(L.mont_mul(A, B)) == [x * y % P for x, y in zip(xs, ys)]
    assert L.unpack_mont(L.add_mod(A, B)) == [(x + y) % P for x, y in zip(xs, ys)]
    assert L.unpack_mont(L.sub_mod(A, B)) == [(x - y) % P for x, y in zip(xs, ys)]
    assert L.unpack_mont(L.inv_mod(A)) == [pow(x, -1, P) if x else 0 for x in xs]


def test_fq2_ops_match_oracle():
    x, y = rand_fq2(), rand_fq2()
    X, Y = T.f2_const(x), T.f2_const(y)

    @jax.jit
    def suite(X, Y):
        return T.f2_mul(X, Y), T.f2_inv(X), T.f2_sqr(X), T.f2_mul_xi(X)

    mul, inv, sqr, xi = suite(X, Y)

    def to_oracle(p):
        return Fq2(L.unpack_mont(p[0])[0], L.unpack_mont(p[1])[0])

    assert to_oracle(mul) == x * y
    assert to_oracle(inv) == x.inv()
    assert to_oracle(sqr) == x.square()
    assert to_oracle(xi) == x * Fq2(1, 1)


@pytest.mark.skipif(not HEAVY, reason="sqrt program jit: set CS_TPU_HEAVY=1 (covered by the heavy hash-to-curve tier)")
def test_fq2_sqrt_of_square():
    x = rand_fq2()
    s = x.square()
    r = jax.jit(T.f2_sqrt)(T.f2_const(s))
    rr = Fq2(L.unpack_mont(r[0])[0], L.unpack_mont(r[1])[0])
    assert rr.square() == s
    assert bool(np.asarray(jax.jit(T.f2_is_square)(T.f2_const(s))))


def test_fq12_mul_matches_oracle():
    def rf6():
        return Fq6(rand_fq2(), rand_fq2(), rand_fq2())
    x, y = Fq12(rf6(), rf6()), Fq12(rf6(), rf6())
    got = jax.jit(T.f12_mul)(T.f12_const(x), T.f12_const(y))
    assert T.f12_to_oracle(got) == x * y


def test_g1_complete_add_matches_oracle():
    ks = [rng.randrange(1, R_ORDER) for _ in range(4)]
    pts = [G1_GENERATOR.mult(k) for k in ks]
    pts[2] = G1Point.inf()  # identity handling
    packed = PT.g1_pack(pts)
    flipped = jax.tree_util.tree_map(lambda a: a[::-1].copy(), packed)
    out = jax.jit(PT.g1_add)(packed, flipped)
    for i in range(4):
        got = PT.g1_unpack(jax.tree_util.tree_map(lambda a: a[i], out))
        assert got == pts[i] + pts[3 - i]


def test_g1_tree_sum_matches_oracle():
    ks = [rng.randrange(1, R_ORDER) for _ in range(5)]  # odd: exercises pad
    pts = [G1_GENERATOR.mult(k) for k in ks]
    got = PT.g1_unpack(jax.jit(PT.g1_tree_sum)(PT.g1_pack(pts)))
    exp = G1Point.inf()
    for p in pts:
        exp = exp + p
    assert got == exp


def test_g2_scalar_mul_matches_oracle():
    k = 98765
    bits = np.array([int(c) for c in bin(k)[2:]], dtype=np.uint32)
    q = G2_GENERATOR.mult(321)
    got = PT.g2_unpack(jax.jit(
        lambda p: PT.g2_scalar_mul(p, bits))(PT.g2_pack([q])))
    # leading batch axis of 1
    assert got == q.mult(k)


# ---------------------------------------------------------------------------
# Heavy tier
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not HEAVY, reason="set CS_TPU_HEAVY=1 (cold compile is minutes)")
def test_pairing_bilinearity():
    """Bilinearity through the STAGED pipeline - the production path.

    The monolithic ``jax.jit(pairing_check)`` cannot compile on a weak
    XLA:CPU host (LLVM out-of-memory after ~40 min; measured round 4),
    so this exercises the same math as the pipeline of bounded programs
    the real verification path dispatches.  Inputs carry a (pairs,
    batch=1) shape; the lane bucket pads the batch axis internally.
    """
    import numpy as np
    import jax.numpy as jnp
    from consensus_specs_tpu.ops.jax_bls import pairing as PR

    a = rng.randrange(2, R_ORDER)

    def staged_check(pairs):
        g1 = PT.g1_pack([p for p, _ in pairs])
        g2 = PT.g2_pack([q for _, q in pairs])
        degen = jnp.array([[p.infinity or q.infinity] for p, q in pairs])
        px = g1[0][:, None]
        py = g1[1][:, None]
        q = ((g2[0][0][:, None], g2[0][1][:, None]),
             (g2[1][0][:, None], g2[1][1][:, None]))
        out = np.asarray(PR.staged_pairing_check(px, py, q, degen))
        return bool(out[0])

    assert staged_check([(G1_GENERATOR, G2_GENERATOR),
                         (-G1_GENERATOR, G2_GENERATOR)])
    assert staged_check([(G1_GENERATOR.mult(a), G2_GENERATOR),
                         (G1_GENERATOR, -(G2_GENERATOR.mult(a)))])
    assert not staged_check([(G1_GENERATOR.mult(a), G2_GENERATOR),
                             (G1_GENERATOR, G2_GENERATOR)])


@pytest.mark.skipif(not HEAVY, reason="set CS_TPU_HEAVY=1 (cold compile is minutes)")
def test_jax_backend_matches_py():
    from consensus_specs_tpu.utils import bls
    from consensus_specs_tpu.ops import bls_jax

    bls.use_py()
    pks = [bls.SkToPk(i) for i in (1, 2, 3)]
    msg = b"backend-parity"
    agg = bls.Aggregate([bls.Sign(i, msg) for i in (1, 2, 3)])
    assert bls.FastAggregateVerify(pks, msg, agg)
    out = bls_jax.verify_aggregates_batch([
        (pks, msg, agg),
        (pks, b"wrong", agg),
        ([pks[0]], msg, bls.Sign(1, msg)),
    ])
    assert out == [True, False, True]
    # infinity pubkey rejected per KeyValidate
    assert not bls_jax.FastAggregateVerify(
        [pks[0], b"\xc0" + b"\x00" * 47], msg, agg)
