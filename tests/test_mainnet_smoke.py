"""Mainnet-preset smoke tests.

The conformance suites default to the minimal preset (like the reference
CI matrix); this module pins the mainnet-preset constants and exercises
one real transition so preset plumbing regressions cannot hide.
Run everything mainnet with `pytest --preset mainnet`.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import hash_tree_root


def test_mainnet_preset_constants():
    spec = build_spec("phase0", "mainnet")
    assert spec.SLOTS_PER_EPOCH == 32
    assert spec.MAX_ATTESTATIONS == 128
    assert spec.MAX_VALIDATORS_PER_COMMITTEE == 2048
    assert spec.SHUFFLE_ROUND_COUNT == 90
    altair = build_spec("altair", "mainnet")
    assert altair.SYNC_COMMITTEE_SIZE == 512
    deneb = build_spec("deneb", "mainnet")
    assert deneb.MAX_BLOBS_PER_BLOCK == 6
    assert deneb.FIELD_ELEMENTS_PER_BLOB == 4096
    # mainnet gindices match the protocol constants too (depth identical)
    assert altair.FINALIZED_ROOT_GINDEX == 105
    assert altair.CURRENT_SYNC_COMMITTEE_GINDEX == 54


def test_mainnet_empty_block_transition():
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot, state_transition_and_sign_block)
    spec = build_spec("phase0", "mainnet")
    old = bls.bls_active
    bls.bls_active = False
    try:
        # small registry: committee math must still hold on mainnet shapes
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 256,
            spec.MAX_EFFECTIVE_BALANCE)
        pre_root = hash_tree_root(state)
        block = build_empty_block_for_next_slot(spec, state)
        state_transition_and_sign_block(spec, state, block)
        assert state.slot == 1
        assert hash_tree_root(state) != pre_root
    finally:
        bls.bls_active = old


def test_mainnet_capella_state_shape():
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    spec = build_spec("capella", "mainnet")
    old = bls.bls_active
    bls.bls_active = False
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 600,
            spec.MAX_EFFECTIVE_BALANCE)
        assert len(state.current_sync_committee.pubkeys) == 512
        assert spec.MAX_WITHDRAWALS_PER_PAYLOAD == 16
        assert state.next_withdrawal_index == 0
    finally:
        bls.bls_active = old
