"""Raw-snappy codec tests: python/native differential + format edges.

Reference role: the ``python-snappy``/libsnappy dependency
(``gen_runner.py:421-426``).
"""
import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.utils import snappy


CASES = [
    b"",
    b"a",
    b"hello world, hello world, hello world",
    b"\x00" * 100000,
    bytes(random.Random(7).randrange(256) for _ in range(5000)),
    (b"abcd" * 1000) + bytes(random.Random(8).randrange(256)
                             for _ in range(500)),
    bytes(random.Random(9).randrange(4) for _ in range(70000)),
]


@pytest.mark.parametrize("data", CASES, ids=range(len(CASES)))
def test_roundtrip(data):
    assert snappy.decompress(snappy.compress(data)) == data


@pytest.mark.parametrize("data", CASES, ids=range(len(CASES)))
def test_python_and_native_interoperate(data):
    """Either codec must decode the other's output (the format allows
    different encodings; the payload must match)."""
    z_py = snappy._py_compress(data)
    assert snappy._py_decompress(z_py) == data
    assert snappy.decompress(z_py) == data
    if snappy._native is not None:
        z = snappy.compress(data)
        assert snappy._py_decompress(z) == data


def test_zero_heavy_payload_compresses():
    data = b"\x00" * 50000
    assert len(snappy.compress(data)) < len(data) // 10


def test_malformed_input_rejected():
    with pytest.raises(Exception):
        snappy.decompress(b"\x05\x02\x01\x00")  # copy beyond output start
    with pytest.raises(Exception):
        # announced length 5 but no body
        snappy._py_decompress(b"\x05")


def test_ssz_state_payload_roundtrip():
    """End-to-end on a real SSZ state body."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.utils.ssz import serialize
    spec = build_spec("phase0", "minimal")
    state = create_genesis_state(spec, [spec.MAX_EFFECTIVE_BALANCE] * 32,
                                 spec.MAX_EFFECTIVE_BALANCE)
    body = serialize(state)
    z = snappy.compress(body)
    assert snappy.decompress(z) == body
    assert len(z) < len(body) // 2  # states are highly compressible
