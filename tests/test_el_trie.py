"""keccak / RLP / Merkle-Patricia trie + EL block hash tests.

Reference role: the external eth_hash/rlp/trie packages the reference
imports in ``test/helpers/execution_payload.py:1-4``; anchors are the
universally-known keccak256("") and empty-trie-root constants.
"""
import pytest

from consensus_specs_tpu.utils.keccak import keccak256
from consensus_specs_tpu.utils.el_trie import (
    EMPTY_TRIE_ROOT, indexed_trie_root, rlp_encode, trie_root)


def test_keccak_anchors():
    assert keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470")
    # 200-byte input crosses the 136-byte rate boundary (two permutations)
    two_block = keccak256(b"\xab" * 200)
    assert two_block != keccak256(b"\xab" * 136)
    assert len(two_block) == 32


def test_rlp_encoding_rules():
    assert rlp_encode(b"") == b"\x80"
    assert rlp_encode(0) == b"\x80"                 # ints: minimal big-endian
    assert rlp_encode(b"\x00") == b"\x00" * 1       # single byte < 0x80: as-is
    assert rlp_encode(b"\x7f") == b"\x7f"
    assert rlp_encode(b"\x80") == b"\x81\x80"       # >= 0x80 gets a length tag
    assert rlp_encode(15) == b"\x0f"
    assert rlp_encode(1024) == b"\x82\x04\x00"
    assert rlp_encode([]) == b"\xc0"
    assert rlp_encode([b"cat", b"dog"]) == b"\xc8\x83cat\x83dog"
    long = b"a" * 56
    assert rlp_encode(long) == b"\xb8\x38" + long   # long-form length
    with pytest.raises(ValueError):
        rlp_encode(-1)


def test_empty_trie_root_constant():
    assert EMPTY_TRIE_ROOT.hex() == (
        "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421")
    assert indexed_trie_root([]) == EMPTY_TRIE_ROOT


def test_trie_root_structure_sensitivity():
    # deterministic + insertion-order independent
    pairs = [(rlp_encode(i), bytes([i])) for i in range(20)]
    assert trie_root(pairs) == trie_root(reversed(pairs))
    # value changes move the root
    r1 = indexed_trie_root([b"a", b"b"])
    r2 = indexed_trie_root([b"a", b"c"])
    assert r1 != r2
    # index matters (leaf position), content-equal lists differ by order
    assert indexed_trie_root([b"a", b"b"]) != indexed_trie_root([b"b", b"a"])
    # single-entry trie differs from empty and from two-entry
    assert indexed_trie_root([b"a"]) not in (EMPTY_TRIE_ROOT, r1)


def test_trie_exercises_extension_nodes():
    # keys sharing a long prefix force extension + branch + leaf nodes
    root = trie_root([(b"\x12\x34\x56", b"x"), (b"\x12\x34\x99", b"y")])
    assert root != trie_root([(b"\x12\x34\x56", b"x")])
    # a 17th empty-path entry lands in the branch value slot
    root2 = trie_root([(b"\x12", b"v"), (b"\x12\x34", b"w")])
    assert len(root2) == 32


def test_el_block_hash_is_rlp_keccak():
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.execution_payload import (
        compute_el_block_hash)
    spec = build_spec("bellatrix", "minimal")
    payload = spec.ExecutionPayload()
    h1 = compute_el_block_hash(spec, payload)
    assert len(bytes(h1)) == 32
    # header fields feed the hash
    payload.block_number = 7
    assert compute_el_block_hash(spec, payload) != h1
    # capella appends the withdrawals trie root to the header list
    spec_c = build_spec("capella", "minimal")
    pc = spec_c.ExecutionPayload()
    hc = compute_el_block_hash(spec_c, pc)
    w = spec_c.Withdrawal(index=1, validator_index=2,
                          address=b"\x03" * 20, amount=4)
    pc.withdrawals = [w]
    assert compute_el_block_hash(spec_c, pc) != hc
