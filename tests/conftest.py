"""Test-session config.

JAX is pinned to an 8-device virtual CPU platform before first import so
sharding/pjit tests exercise real multi-device code paths without TPU
hardware (the driver separately dry-runs the multi-chip path via
``__graft_entry__.dryrun_multichip``).

Test-facing flags mirror the reference harness
(``tests/core/pyspec/eth2spec/test/conftest.py:30-52``):
``--preset``, ``--fork``, ``--disable-bls``, ``--bls-type``.
"""
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Shared persistent XLA compile cache (keyed by jaxlib/libtpu build); the
# BLS pairing programs are big — cache them across pytest runs.  Then pin
# the whole test session to the host-CPU platform: the container's
# accelerator plugin force-selects the tunnel-backed backend via
# jax.config, and tests must never hang on that tunnel.
from consensus_specs_tpu.utils.jax_env import (  # noqa: E402
    setup_compile_cache, force_cpu_platform)
setup_compile_cache()
force_cpu_platform()


def pytest_addoption(parser):
    parser.addoption("--preset", action="store", default="minimal",
                     help="preset to run tests with: minimal or mainnet")
    parser.addoption("--fork", action="store", default=None,
                     help="restrict tests to one fork")
    # BLS is disabled by default for suite speed, exactly like the
    # reference's `make test` (Makefile:118-120); @always_bls tests force
    # signature checks regardless, and --enable-bls turns them on
    # everywhere (the reference's citest mode).
    parser.addoption("--enable-bls", action="store_true", default=False,
                     help="verify BLS signatures in every test")
    parser.addoption("--disable-bls", action="store_true", default=False,
                     help="(default) skip BLS checks where tests allow it")
    parser.addoption("--bls-type", action="store", default="py",
                     choices=["py", "jax", "native", "fastest"],
                     help="BLS backend (native = the C library, the "
                          "reference's milagro/arkworks role)")
    parser.addoption("--compiled", action="store_true", default=False,
                     help="run the conformance suite against the markdown-"
                          "compiled spec ladder (make pyspec output) instead "
                          "of the hand-written classes")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _supervisor_isolation():
    """Supervisor breaker/audit state must not leak across tests: a
    differential test that forces repeated guard or injected fallbacks
    would otherwise open a site's circuit breaker and demote that
    engine for every later test in the process (the counter-asserted
    suites would then see spec-path answers).  Reset is a handful of
    dict clears — negligible per test."""
    yield
    from consensus_specs_tpu import supervisor
    supervisor.reset()


@pytest.fixture
def metrics_diff():
    """Counter snapshot/diff fixture (``test_infra/metrics.py``): yields
    the ``counting`` context manager class; keys absent from a measured
    delta read as 0::

        def test_engine_answered(metrics_diff):
            with metrics_diff() as delta:
                spec.get_head(store)
            assert delta["forkchoice.head{path=engine}"] == 1
    """
    from consensus_specs_tpu.test_infra.metrics import counting
    return counting


def _check_speclint_baseline():
    """Deflake guard: the checked-in ratchet file must be sorted and
    duplicate-free, so re-ratchets (`make speclint-baseline`) always
    produce one-line-per-finding diffs.  An unsorted or duplicated
    baseline makes every ratchet a whole-file rewrite — churn that
    hides the real delta — so it fails the session loudly here rather
    than surviving until a confusing review."""
    import json
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "speclint_baseline.json")
    if not os.path.isfile(path):
        return

    def no_dups(pairs):
        seen = set()
        for key, _ in pairs:
            if key in seen:
                raise AssertionError(
                    f"speclint_baseline.json has a duplicate key: {key!r}"
                    " — deduplicate it (json.load would silently keep "
                    "one and the ratchet count would flap)")
            seen.add(key)
        return dict(pairs)

    with open(path) as f:
        data = json.load(f, object_pairs_hook=no_dups)
    keys = list(data.get("counts", {}))
    assert keys == sorted(keys), (
        "speclint_baseline.json counts are not sorted — run "
        "`make speclint-baseline` (the writer sorts) instead of "
        "editing by hand; unsorted keys turn every re-ratchet into a "
        "whole-file diff")
    assert all(isinstance(n, int) and n >= 1
               for n in data.get("counts", {}).values()), (
        "speclint_baseline.json counts must be positive integers")


def pytest_configure(config):
    # `slow`: excluded from the tier-1 `-m 'not slow'` budget run; still
    # covered by `make citest` / CI (no marker filter there)
    config.addinivalue_line(
        "markers", "slow: long-running test excluded from the fast tier")
    _check_speclint_baseline()
    from consensus_specs_tpu.test_infra import context as ctx
    ctx.DEFAULT_TEST_PRESET = config.getoption("--preset")
    ctx.DEFAULT_BLS_ACTIVE = (config.getoption("--enable-bls")
                              and not config.getoption("--disable-bls"))
    ctx.DEFAULT_BLS_TYPE = config.getoption("--bls-type")
    only_fork = config.getoption("--fork")
    if only_fork:
        ctx.ONLY_FORK = only_fork
    if config.getoption("--compiled"):
        from consensus_specs_tpu.forks import use_compiled_registry
        use_compiled_registry()
