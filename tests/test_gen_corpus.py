"""Corpus factory contract tests.

Covers the gen_runner pool/resume mechanics the orchestrator builds
on, the corpus factory's byte-identity against the serial
per-generator path, the cross-case accelerations' censuses
(sign memo, per-case RLC fold), the worker->parent counter-delta
plumbing, the locked diagnostics merge, and the fidelity replayer's
mismatch detection.
"""
import json
import multiprocessing
import os
import shutil

import pytest

from consensus_specs_tpu.gen import gen_runner
from consensus_specs_tpu.gen import corpus as corpus_mod
from consensus_specs_tpu.gen import replay as replay_mod
from consensus_specs_tpu.gen.gen_from_tests import state_test_providers
from consensus_specs_tpu.obs import registry
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import snappy


SANITY_MODS = {"phase0": {"blocks": "tests.phase0.sanity.test_blocks",
                          "slots": "tests.phase0.sanity.test_slots"}}


def _sanity_cases(fork_list=("phase0",)):
    provs = state_test_providers("sanity", SANITY_MODS, presets=("minimal",))
    cases, _ = gen_runner.collect_cases(provs, ["minimal"], list(fork_list))
    return cases


def _tree_digest(root):
    """Stable content digest of every file under <root>/tests."""
    import hashlib
    h = hashlib.sha256()
    base = os.path.join(root, "tests")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames.sort()
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, base).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# resume semantics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("workers", [1, 2], ids=["serial", "pool"])
def test_resume_regenerates_exactly_the_incomplete_case(tmp_path, workers):
    """A crash mid-case leaves the INCOMPLETE tag; the next run
    regenerates exactly that case and skips every complete one."""
    out = str(tmp_path)
    cases = _sanity_cases()[:6]
    outcomes, _ = gen_runner.run_cases(cases, out, workers=workers)
    assert {r for _, r, _ in outcomes} == {"generated"}

    victim = cases[2]
    victim_dir = os.path.join(out, victim.dir_path())
    # simulate a crash mid-write: tag present, parts half-gone
    with open(os.path.join(victim_dir, "INCOMPLETE"), "wb") as f:
        f.write(b"INCOMPLETE")
    for name in os.listdir(victim_dir):
        if name != "INCOMPLETE":
            os.remove(os.path.join(victim_dir, name))

    outcomes, _ = gen_runner.run_cases(cases, out, workers=workers)
    by_case = {c.dir_path(): r for c, r, _ in outcomes}
    assert by_case[victim.dir_path()] == "generated"
    assert sorted(set(by_case.values())) == ["generated", "skipped"]
    assert sum(1 for r in by_case.values() if r == "generated") == 1
    assert not os.path.exists(os.path.join(victim_dir, "INCOMPLETE"))
    assert os.path.exists(os.path.join(victim_dir, "post.ssz_snappy")) or \
        os.listdir(victim_dir)


@pytest.mark.parametrize("workers", [1, 2], ids=["serial", "pool"])
def test_force_regenerates_complete_cases(tmp_path, workers):
    out = str(tmp_path)
    cases = _sanity_cases()[:4]
    gen_runner.run_cases(cases, out, workers=workers)
    # without force: all skip
    outcomes, _ = gen_runner.run_cases(cases, out, workers=workers)
    assert {r for _, r, _ in outcomes} == {"skipped"}
    # collect_cases(force=True) removes the complete dirs up front
    provs = state_test_providers("sanity", SANITY_MODS, presets=("minimal",))
    forced, _ = gen_runner.collect_cases(
        provs, ["minimal"], ["phase0"], force=True, output_dir=out)
    keep = {c.dir_path() for c in cases}
    forced = [c for c in forced if c.dir_path() in keep]
    outcomes, _ = gen_runner.run_cases(forced, out, workers=workers)
    assert {r for _, r, _ in outcomes} == {"generated"}


# ---------------------------------------------------------------------------
# worker-side counters ride back to the parent
# ---------------------------------------------------------------------------

class _ErrCase:
    """Minimal TestCase stand-in whose body fails with an assertion."""
    preset_name = "minimal"
    fork_name = "phase0"
    exec_fork = "phase0"
    batchable = False
    generator_name = "errgen"

    def __init__(self, name="boom"):
        self.name = name

    def dir_path(self):
        return f"tests/minimal/phase0/errgen/err/suite/{self.name}"

    def case_fn(self):
        raise AssertionError("deliberate case failure")


def test_pool_worker_counter_deltas_booked_in_parent(tmp_path):
    """gen.case_errors bumped inside a fork-pool child must land in the
    PARENT registry (satellite: lost worker-side obs counters)."""
    if "fork" not in multiprocessing.get_all_start_methods():
        pytest.skip("fork start method unavailable")
    cases = [_ErrCase("a"), _ErrCase("b"), _ErrCase("c")]
    with counting() as delta:
        outcomes, error_log = gen_runner.run_cases(
            cases, str(tmp_path), workers=2)
    assert {r for _, r, _ in outcomes} == {"error"}
    assert len(error_log) == 3
    assert delta["gen.case_errors{error=AssertionError}"] == 3


def test_book_flat_deltas_round_trips_series_keys():
    registry.book_flat_deltas({"x.some_counter{a=1,b=two}": 4,
                               "x.plain": 2,
                               "x.negative": -5})
    vals = registry.counter_values()
    assert vals["x.some_counter{a=1,b=two}"] == 4
    assert vals["x.plain"] == 2
    assert "x.negative" not in vals  # negative deltas dropped


# ---------------------------------------------------------------------------
# diagnostics / error-log merge is lost-update-safe
# ---------------------------------------------------------------------------

def _report_worker(args):
    out, name = args
    gen_runner.write_run_reports(
        name, out,
        {"collected": 1, "generated": 1, "skipped": 0, "errors": 0,
         "test_identifiers": [f"tests/x/{name}"]},
        [{"case": f"tests/x/{name}", "error": f"err-{name}\n"}],
        timings={f"tests/x/{name}": 1.0})


def test_concurrent_run_reports_lose_no_entries(tmp_path):
    """16 processes merging diagnostics + error logs concurrently: every
    generator's entry and every error line survives (satellite: the
    read-modify-write lost-update race)."""
    out = str(tmp_path)
    names = [f"gen{i:02d}" for i in range(16)]
    ctx = multiprocessing.get_context("fork")
    with ctx.Pool(8) as pool:
        pool.map(_report_worker, [(out, n) for n in names])
    with open(os.path.join(out, "diagnostics_obj.json")) as f:
        diag = json.load(f)
    assert sorted(diag) == names
    for n in names:
        assert diag[n]["generated"] == 1
        assert diag[n]["timings"] == {f"tests/x/{n}": 1.0}
        with open(os.path.join(
                out, f"testgen_error_log_{n}.txt")) as f:
            assert f"err-{n}" in f.read()


def test_timings_survive_runs_without_fresh_timings(tmp_path):
    """A resumed run (everything skipped -> no new timings) must not
    erase the persisted cost profile the scheduler depends on."""
    out = str(tmp_path)
    diagnostics = {"collected": 1, "generated": 1, "skipped": 0,
                   "errors": 0, "test_identifiers": ["tests/x/a"]}
    gen_runner.write_run_reports("g", out, diagnostics, [],
                                 timings={"tests/x/a": 2.5})
    diagnostics = {"collected": 1, "generated": 0, "skipped": 1,
                   "errors": 0, "test_identifiers": []}
    gen_runner.write_run_reports("g", out, diagnostics, [], timings={})
    with open(os.path.join(out, "diagnostics_obj.json")) as f:
        assert json.load(f)["g"]["timings"] == {"tests/x/a": 2.5}


# ---------------------------------------------------------------------------
# cost-aware scheduler
# ---------------------------------------------------------------------------

def test_schedule_longest_first_with_unknowns_up_front():
    class _C:
        def __init__(self, p):
            self._p = p

        def dir_path(self):
            return self._p

    cases = [_C("fast"), _C("slow"), _C("unknown"), _C("mid")]
    profile = {"fast": 0.1, "slow": 30.0, "mid": 3.0}
    ordered = corpus_mod.schedule_cases(cases, profile)
    assert [c.dir_path() for c in ordered] == \
        ["unknown", "slow", "mid", "fast"]


def test_load_cost_profile_unions_all_generators(tmp_path):
    out = str(tmp_path)
    gen_runner.write_run_reports(
        "g1", out, {"collected": 1, "generated": 1, "skipped": 0,
                    "errors": 0, "test_identifiers": []},
        [], timings={"tests/a": 1.0})
    gen_runner.write_run_reports(
        "g2", out, {"collected": 1, "generated": 1, "skipped": 0,
                    "errors": 0, "test_identifiers": []},
        [], timings={"tests/b": 2.0})
    assert corpus_mod.load_cost_profile(out) == \
        {"tests/a": 1.0, "tests/b": 2.0}


# ---------------------------------------------------------------------------
# cross-case accelerations: censuses + byte identity
# ---------------------------------------------------------------------------

def test_sign_memo_hits_and_is_bypassed_in_stub_mode():
    from consensus_specs_tpu.test_infra import signing
    from consensus_specs_tpu.utils import bls
    signing.clear()
    with counting() as delta:
        s1 = signing.sign(7, b"\x22" * 32)
        s2 = signing.sign(7, b"\x22" * 32)
    assert s1 == s2
    assert delta["gen.sign_memo{result=miss}"] == 1
    assert delta["gen.sign_memo{result=hit}"] == 1
    # stub mode: memo not consulted, not populated
    old = bls.bls_active
    bls.bls_active = False
    try:
        with counting() as delta:
            stub = signing.sign(7, b"\x22" * 32)
        assert stub == bls.STUB_SIGNATURE
        assert delta["gen.sign_memo{result=hit}"] == 0
        assert delta["gen.sign_memo{result=miss}"] == 0
    finally:
        bls.bls_active = old
    assert signing.sign(7, b"\x22" * 32) == s1  # real entry intact


def test_case_fold_reduces_pairings_and_keeps_bytes(tmp_path):
    """The per-case RLC fold must (a) collapse each folded case's
    signature checks into one pairing, (b) replay expected-invalid
    cases synchronously, and (c) leave the emitted tree byte-identical
    to the unfolded run."""
    out_plain = str(tmp_path / "plain")
    out_fold = str(tmp_path / "fold")
    cases = _sanity_cases()
    with counting() as plain_delta:
        gen_runner.run_cases(cases, out_plain, workers=1, fold=False)
    with counting() as fold_delta:
        gen_runner.run_cases(cases, out_fold, workers=1, fold=True)
    assert _tree_digest(out_plain) == _tree_digest(out_fold)
    assert fold_delta["gen.case_batches{path=folded}"] > 0
    # expected-invalid signature cases fall back to the plain path
    assert fold_delta["gen.case_replays"] >= 1
    assert 0 < fold_delta["bls.pairings"] < plain_delta["bls.pairings"]


class _SystemExitCase:
    """A case guarding an expected-rejection with SystemExit (the
    light_client test_invalid_signature_rejected shape): the plain path
    rejects the bad signature, but a folded scope answers True
    optimistically and the guard fires."""
    preset_name = "minimal"
    fork_name = "phase0"
    exec_fork = "phase0"
    batchable = True
    generator_name = "exitgen"
    name = "must_reject"

    def dir_path(self):
        return "tests/minimal/phase0/exitgen/err/suite/must_reject"

    def case_fn(self):
        from consensus_specs_tpu.utils import bls
        if bls.Verify(bls.SkToPk(1), b"\x01" * 32,
                      bls.Sign(2, b"\x02" * 32)):
            raise SystemExit("invalid signature must fail")
        yield "description", gen_runner.YamlPart(
            value="rejected as it must be")


def test_fold_replays_systemexit_guard_instead_of_dying(tmp_path):
    """Under the fold a SystemExit rejection guard is a deferral
    artifact: the case must replay on the plain path (where the guard
    stays quiet), not kill the whole corpus process."""
    from consensus_specs_tpu.utils import bls
    old = bls.bls_active
    bls.bls_active = True  # alt_return would accept everything
    try:
        with counting() as delta:
            outcomes, error_log = gen_runner.run_cases(
                [_SystemExitCase()], str(tmp_path), workers=1, fold=True)
        assert [r for _, r, _ in outcomes] == ["generated"]
        assert not error_log
        assert delta["gen.case_replays"] == 1
    finally:
        bls.bls_active = old
    # outside a fold a SystemExit is a real abort and must escape
    abort = _SystemExitCase()
    abort.case_fn = lambda: (_ for _ in ()).throw(
        SystemExit("real abort"))
    shutil.rmtree(tmp_path)
    with pytest.raises(SystemExit):
        gen_runner.run_cases([abort], str(tmp_path), workers=1,
                             fold=False)


def test_corpus_factory_matches_serial_generators(tmp_path):
    """End-to-end: run_corpus over two real generators equals the
    per-generator serial path byte-for-byte, and persists the timing
    profile a second run schedules from."""
    out_corpus = str(tmp_path / "corpus")
    out_serial = str(tmp_path / "serial")
    gens = ["genesis", "shuffling"]
    summary = corpus_mod.run_corpus(
        out_corpus, generator_names=gens, preset_list=["minimal"],
        fork_list=["phase0"], workers=2)
    assert summary["errors"] == 0
    assert summary["generated"] > 0
    for gen_dir in gens:
        mod = corpus_mod._load_entrypoint(gen_dir)
        cases, _ = gen_runner.collect_cases(
            mod.providers(), ["minimal"], ["phase0"])
        gen_runner.run_cases(cases, out_serial, workers=1)
    assert _tree_digest(out_corpus) == _tree_digest(out_serial)
    # profile persisted under each generator's diagnostics name
    profile = corpus_mod.load_cost_profile(out_corpus)
    assert len(profile) == summary["generated"]
    # resume: everything skips
    summary2 = corpus_mod.run_corpus(
        out_corpus, generator_names=gens, preset_list=["minimal"],
        fork_list=["phase0"], workers=2, prewarm_parent=False)
    assert summary2["generated"] == 0
    assert summary2["skipped"] == summary["generated"]


def test_prewarm_seeds_parent_caches():
    from consensus_specs_tpu.test_infra import context as ctx
    from consensus_specs_tpu.test_infra import keys

    class _C:
        preset_name = "minimal"
        exec_fork = "phase0"

    warm = corpus_mod.prewarm([_C()], keys_limit=8)
    assert warm["specs"] == 1
    assert any(k[0] == "phase0" and k[1] == "minimal"
               and k[3] == "default_balances" for k in ctx._state_cache)
    assert all(keys.privkeys[i] in keys._pubkey_cache for i in range(8))


# ---------------------------------------------------------------------------
# fidelity replayer
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sanity_corpus(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("replay_corpus"))
    gen_runner.run_cases(_sanity_cases(), out, workers=1)
    return out


def test_replayer_accepts_faithful_corpus(sanity_corpus):
    summary = replay_mod.replay_tree(sanity_corpus)
    assert summary["mismatches"] == []
    assert summary["replayed"] > 0


def test_replayer_detects_tampered_post_state(sanity_corpus, tmp_path):
    out = str(tmp_path / "tampered")
    shutil.copytree(sanity_corpus, out)
    post = None
    for case_dir, _, _, runner, handler in replay_mod.walk_cases(out):
        if runner == "sanity" and handler == "slots":
            candidate = os.path.join(case_dir, "post.ssz_snappy")
            if os.path.exists(candidate):
                post = candidate
                break
    assert post is not None
    raw = bytearray(snappy.decompress(open(post, "rb").read()))
    raw[100] ^= 0xFF
    with open(post, "wb") as f:
        f.write(snappy.compress(bytes(raw)))
    summary = replay_mod.replay_tree(out)
    assert len(summary["mismatches"]) == 1
    assert "state root differs" in summary["mismatches"][0]


def test_replayer_rejects_incomplete_case(sanity_corpus, tmp_path):
    out = str(tmp_path / "incomplete")
    shutil.copytree(sanity_corpus, out)
    case_dir = next(replay_mod.walk_cases(out))[0]
    with open(os.path.join(case_dir, "INCOMPLETE"), "wb") as f:
        f.write(b"INCOMPLETE")
    summary = replay_mod.replay_tree(out)
    assert any("INCOMPLETE" in m for m in summary["mismatches"])


def test_replayer_flags_wrongly_accepted_invalid_case(sanity_corpus,
                                                      tmp_path):
    """A case whose post was deleted claims the input must be rejected;
    the replayer must flag the (actually valid) input as a mismatch."""
    out = str(tmp_path / "misflagged")
    shutil.copytree(sanity_corpus, out)
    victim = None
    for case_dir, _, _, runner, handler in replay_mod.walk_cases(out):
        if runner == "sanity" and handler == "blocks" \
                and os.path.exists(os.path.join(case_dir,
                                                "post.ssz_snappy")):
            victim = case_dir
            break
    assert victim is not None
    os.remove(os.path.join(victim, "post.ssz_snappy"))
    summary = replay_mod.replay_tree(out)
    assert any("was accepted" in m for m in summary["mismatches"])
