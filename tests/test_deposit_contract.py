"""Deposit-contract accumulator vs SSZ merkleization.

Reference model: the dapp/web3 tests around
``solidity_deposit_contract/deposit_contract.sol`` — the contract's
incremental root must equal the SSZ ``List[DepositData, 2**32]``
hash_tree_root the beacon chain checks in ``process_deposit``
(``specs/phase0/deposit-contract.md``).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from solidity_deposit_contract.contract_model import DepositContractModel
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.utils.ssz import hash_tree_root, List
from consensus_specs_tpu.test_infra.keys import pubkeys, privkeys
from consensus_specs_tpu.test_infra.deposits import build_deposit_data
from consensus_specs_tpu.utils.hash_function import hash

CONTRACT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "solidity_deposit_contract")


def _spec():
    return build_spec("phase0", "minimal")


def test_incremental_root_matches_ssz_list_root():
    spec = _spec()
    contract = DepositContractModel()
    DepositDataList = List[spec.DepositData,
                           2 ** spec.DEPOSIT_CONTRACT_TREE_DEPTH]
    deposit_data_list = []
    for i in range(8):
        wc = spec.BLS_WITHDRAWAL_PREFIX + hash(pubkeys[i])[1:]
        amount = spec.MAX_EFFECTIVE_BALANCE
        data = build_deposit_data(spec, pubkeys[i], privkeys[i], amount, wc,
                                  signed=True)
        deposit_data_list.append(data)
        contract.deposit(bytes(data.pubkey),
                         bytes(data.withdrawal_credentials),
                         int(data.amount), bytes(data.signature))
        # after every deposit, the contract root equals the SSZ list root
        assert contract.get_deposit_root() == \
            hash_tree_root(DepositDataList(deposit_data_list)), i
        assert contract.get_deposit_count() == \
            len(deposit_data_list).to_bytes(8, "little")


def test_deposit_data_root_reconstruction():
    """The contract's in-EVM SSZ reconstruction must equal the real
    hash_tree_root(DepositData)."""
    spec = _spec()
    wc = spec.BLS_WITHDRAWAL_PREFIX + hash(pubkeys[0])[1:]
    data = build_deposit_data(spec, pubkeys[0], privkeys[0],
                              spec.MAX_EFFECTIVE_BALANCE, wc, signed=True)
    assert DepositContractModel.deposit_data_root(
        bytes(data.pubkey), bytes(data.withdrawal_credentials),
        int(data.amount), bytes(data.signature)) == hash_tree_root(data)


def test_empty_contract_root_matches_empty_list():
    spec = _spec()
    contract = DepositContractModel()
    DepositDataList = List[spec.DepositData,
                           2 ** spec.DEPOSIT_CONTRACT_TREE_DEPTH]
    assert contract.get_deposit_root() == hash_tree_root(DepositDataList())


def test_contract_proofs_feed_process_deposit():
    """End to end: a deposit proven against the contract root passes
    process_deposit."""
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.test_infra.deposits import deposit_from_context
    from consensus_specs_tpu.utils import bls
    spec = _spec()
    bls.bls_active = False
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 32,
            spec.MAX_EFFECTIVE_BALANCE)
        contract = DepositContractModel()
        new_index = len(state.validators)
        wc = spec.BLS_WITHDRAWAL_PREFIX + hash(pubkeys[new_index])[1:]
        data = build_deposit_data(spec, pubkeys[new_index],
                                  privkeys[new_index],
                                  spec.MAX_EFFECTIVE_BALANCE, wc, signed=True)
        contract.deposit(bytes(data.pubkey),
                         bytes(data.withdrawal_credentials),
                         int(data.amount), bytes(data.signature))
        deposit, root, _ = deposit_from_context(spec, [data], 0)
        assert root == contract.get_deposit_root()
        state.eth1_data.deposit_root = contract.get_deposit_root()
        state.eth1_data.deposit_count = 1
        state.eth1_deposit_index = 0
        pre_count = len(state.validators)
        spec.process_deposit(state, deposit)
        assert len(state.validators) == pre_count + 1
    finally:
        bls.bls_active = True


# ---------------------------------------------------------------------------
# ABI artifact + tester round trip (reference: deposit_contract.json +
# web3_tester/; Makefile:164-181)
# ---------------------------------------------------------------------------

def _signed_deposit_args(spec, index, amount_gwei):
    data = build_deposit_data(
        spec, pubkeys[index], privkeys[index], amount_gwei,
        spec.BLS_WITHDRAWAL_PREFIX + hash(pubkeys[index])[1:], signed=True)
    return data, (bytes(data.pubkey), bytes(data.withdrawal_credentials),
                  bytes(data.signature), bytes(hash_tree_root(data)))


def test_abi_artifact_matches_contract_interface():
    import json
    import re
    abi_path = os.path.join(CONTRACT_DIR, "deposit_contract.json")
    with open(abi_path) as f:
        artifact = json.load(f)
    abi_names = {e["name"] for e in artifact["abi"] if e["type"] == "function"}
    sol = open(os.path.join(CONTRACT_DIR, "deposit_contract.sol")).read()
    sol_fns = set(re.findall(r"function (\w+)\(", sol)) - {"to_little_endian_64"}
    assert abi_names == sol_fns, (abi_names, sol_fns)
    events = [e for e in artifact["abi"] if e["type"] == "event"]
    assert [e["name"] for e in events] == ["DepositEvent"]
    assert [i["name"] for i in events[0]["inputs"]] == [
        "pubkey", "withdrawal_credentials", "amount", "signature", "index"]


def test_abi_tester_round_trip_against_spec_roots():
    """Deposits driven through the ABI tester produce the same root the
    beacon chain computes over List[DepositData]."""
    from solidity_deposit_contract.abi_tester import (
        DepositContractTester, GWEI)
    spec = build_spec("phase0", "minimal")
    tester = DepositContractTester()
    deposit_data_list = []
    DepositDataList = List[spec.DepositData, 2**32]
    for i in range(4):
        amount_gwei = int(spec.MAX_EFFECTIVE_BALANCE)
        data, (pubkey, creds, sig, root) = _signed_deposit_args(
            spec, i, amount_gwei)
        tester.deposit(pubkey, creds, sig, root,
                       value_wei=amount_gwei * GWEI)
        deposit_data_list.append(data)
        expected = hash_tree_root(DepositDataList(deposit_data_list))
        assert tester.get_deposit_root() == bytes(expected)
        assert int.from_bytes(tester.get_deposit_count(), "little") == i + 1
    # event log mirrors the deposit sequence
    assert [int.from_bytes(e["index"], "little") for e in tester.logs] == \
        [0, 1, 2, 3]


def test_abi_tester_rejects_bad_inputs():
    from solidity_deposit_contract.abi_tester import (
        DepositContractTester, AbiError, GWEI)
    spec = build_spec("phase0", "minimal")
    amount_gwei = int(spec.MAX_EFFECTIVE_BALANCE)
    _, (pubkey, creds, sig, root) = _signed_deposit_args(spec, 0, amount_gwei)
    tester = DepositContractTester()
    import pytest
    with pytest.raises(AbiError):   # short pubkey
        tester.deposit(pubkey[:-1], creds, sig, root, amount_gwei * GWEI)
    with pytest.raises(AbiError):   # below 1-ether minimum
        tester.deposit(pubkey, creds, sig, root, GWEI)
    with pytest.raises(AbiError):   # non-gwei-multiple value
        tester.deposit(pubkey, creds, sig, root, amount_gwei * GWEI + 1)
    with pytest.raises(AbiError):   # wrong data root
        tester.deposit(pubkey, creds, sig, b"\x00" * 32, amount_gwei * GWEI)
    assert tester.logs == []


def test_supports_interface():
    from solidity_deposit_contract.abi_tester import DepositContractTester
    tester = DepositContractTester()
    assert tester.supportsInterface(bytes.fromhex("01ffc9a7"))  # ERC165
    assert not tester.supportsInterface(b"\xff\xff\xff\xff")
