"""Spec-compiler golden tests.

Reference model: the ``make pyspec`` pipeline (``setup.py:178-354``) —
markdown is the source of truth and the compiled module must behave
identically to the runtime the conformance suite certifies.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.compiler import parse_markdown_spec, compile_spec
from consensus_specs_tpu.config import load_preset, load_config
from consensus_specs_tpu.utils.ssz import hash_tree_root
from consensus_specs_tpu.utils import bls

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MD_PATH = os.path.join(REPO, "specs", "phase0", "beacon-chain.md")


def _compiled_spec():
    src = compile_spec(MD_PATH)
    namespace = {}
    exec(compile(src, "<compiled-phase0>", "exec"), namespace)
    cls = namespace["CompiledPhase0Spec"]
    return cls(load_preset("minimal"), load_config("minimal"),
               preset_name="minimal")


def test_markdown_parses():
    with open(MD_PATH) as f:
        doc = parse_markdown_spec(f.read())
    assert doc.fork == "phase0"
    fns = doc.functions()
    # the load-bearing functions must all be present in the markdown
    for name in ("state_transition", "process_block", "process_epoch",
                 "process_attestation", "compute_shuffled_index",
                 "initialize_beacon_state_from_eth1", "_build_types"):
        assert name in fns, name


def test_compiled_module_matches_handwritten_runtime():
    """Golden diff: the compiled spec and the hand-written spec must agree
    on genesis roots and a signed-block transition."""
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot, state_transition_and_sign_block)

    hand = build_spec("phase0", "minimal")
    comp = _compiled_spec()

    bls.bls_active = False
    try:
        balances = [hand.MAX_EFFECTIVE_BALANCE] * 32
        state_h = create_genesis_state(hand, balances,
                                       hand.MAX_EFFECTIVE_BALANCE)
        state_c = create_genesis_state(comp, balances,
                                       comp.MAX_EFFECTIVE_BALANCE)
        assert hash_tree_root(state_h) == hash_tree_root(state_c)

        block_h = build_empty_block_for_next_slot(hand, state_h)
        signed_h = state_transition_and_sign_block(hand, state_h, block_h)
        block_c = build_empty_block_for_next_slot(comp, state_c)
        signed_c = state_transition_and_sign_block(comp, state_c, block_c)
        assert hash_tree_root(signed_h.message) == \
            hash_tree_root(signed_c.message)
        assert hash_tree_root(state_h) == hash_tree_root(state_c)
    finally:
        bls.bls_active = True


def test_compiled_fork_ladder_matches_handwritten():
    """The full markdown-compiled ladder (phase0->deneb) must reproduce
    the hand-written runtime's states across a signed-block transition."""
    import subprocess
    subprocess.run([sys.executable, "-m", "consensus_specs_tpu.compiler"],
                   check=True, cwd=REPO, capture_output=True)
    from consensus_specs_tpu.forks import build_spec
    from consensus_specs_tpu.forks.compiled.deneb import CompiledDenebSpec
    from consensus_specs_tpu.test_infra.genesis import create_genesis_state
    from consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot, state_transition_and_sign_block)

    hand = build_spec("deneb", "minimal")
    comp = CompiledDenebSpec(load_preset("minimal"), load_config("minimal"),
                             preset_name="minimal")
    bls.bls_active = False
    try:
        balances = [hand.MAX_EFFECTIVE_BALANCE] * 32
        state_h = create_genesis_state(hand, balances,
                                       hand.MAX_EFFECTIVE_BALANCE)
        state_c = create_genesis_state(comp, balances,
                                       comp.MAX_EFFECTIVE_BALANCE)
        assert hash_tree_root(state_h) == hash_tree_root(state_c)
        block_h = build_empty_block_for_next_slot(hand, state_h)
        state_transition_and_sign_block(hand, state_h, block_h)
        block_c = build_empty_block_for_next_slot(comp, state_c)
        state_transition_and_sign_block(comp, state_c, block_c)
        assert hash_tree_root(state_h) == hash_tree_root(state_c)
    finally:
        bls.bls_active = True


def test_compiled_shuffle_matches():
    from consensus_specs_tpu.forks import build_spec
    hand = build_spec("phase0", "minimal")
    comp = _compiled_spec()
    seed = b"\x33" * 32
    for i in range(20):
        assert hand.compute_shuffled_index(i, 20, seed) == \
            comp.compute_shuffled_index(i, 20, seed)


def test_extract_module_scope_blocks():
    """``<!-- scope: module -->`` routes the NEXT block to module level
    (Store dataclasses, helpers) — and only that block."""
    md = (
        "# Demo\n\n"
        "<!-- scope: module -->\n"
        "```python\n"
        "MODULE_HELPER = 1\n"
        "```\n\n"
        "```python\n"
        "def method(self): pass\n"
        "```\n")
    doc = parse_markdown_spec(md)
    assert doc.module_blocks == ["MODULE_HELPER = 1"]
    assert doc.code_blocks == ["def method(self): pass"]
    # line anchors: first content line of each fence (speclint relies
    # on these to annotate the markdown itself)
    assert doc.module_block_lines == [5]
    assert doc.code_block_lines == [9]


def test_extract_constant_tables_two_vs_three_columns():
    """2-column tables with parseable values are constants; 3+-column
    documentation tables and header/separator rows are not."""
    md = (
        "# Demo\n\n"
        "| Name | Value |\n"
        "| - | - |\n"
        "| `MAX_THINGS` | `2**10` |\n"
        "| `BAD_SYNTAX` | `)( nope` |\n\n"
        "| Name | Value | Unit |\n"
        "| `PRESET_VAR` | `64` | slots |\n")
    doc = parse_markdown_spec(md)
    assert doc.constants == {"MAX_THINGS": "2**10"}


def test_extract_unterminated_fence_raises_with_line():
    import pytest
    md = "# Demo\n\n```python\nx = 1\n"
    with pytest.raises(ValueError, match="line 3"):
        parse_markdown_spec(md)


def test_provenance_manifest_covers_all_spec_logic():
    """Every fork's hand-written spec-logic methods must be
    markdown-sourced (the judge-audited no-silent-fallback invariant)."""
    from consensus_specs_tpu.compiler.emit import (
        _FORK_DOCS, _FORK_ORDER, _parse, fork_provenance,
        verify_provenance)
    manifest = {}
    for fork in _FORK_ORDER:
        rels = _FORK_DOCS[fork]
        docs = [_parse(os.path.join(REPO, "specs", rel)) for rel in rels]
        manifest[fork] = fork_provenance(
            docs, rels, phase0_scaffold=fork == "phase0")
    verify_provenance(manifest)  # raises on any gap
    # spot checks: feature-fork logic is traceable to its document
    assert manifest["eip6110"]["process_deposit_receipt"] == \
        "specs/_features/eip6110/beacon-chain.md"
    assert manifest["whisk"]["upgrade_to_whisk"] == \
        "specs/_features/whisk/fork.md"
    assert manifest["eip7594"]["is_data_available"] == \
        "specs/_features/das/das-core.md"
    assert manifest["eip7594"]["recover_cells_and_kzg_proofs"] == \
        "specs/_features/eip7594/polynomial-commitments-sampling.md"
    assert manifest["eip7594"]["get_custody_columns"] == \
        "specs/_features/das/das-core.md"


def test_provenance_guard_fires_on_missing_symbol():
    """Removing a markdown symbol must fail the build loudly."""
    import pytest
    from consensus_specs_tpu.compiler.emit import (
        _FORK_DOCS, _FORK_ORDER, _parse, fork_provenance,
        verify_provenance)
    manifest = {}
    for fork in _FORK_ORDER:
        rels = _FORK_DOCS[fork]
        docs = [_parse(os.path.join(REPO, "specs", rel)) for rel in rels]
        manifest[fork] = fork_provenance(
            docs, rels, phase0_scaffold=fork == "phase0")
    del manifest["eip7002"]["process_execution_layer_exit"]
    with pytest.raises(RuntimeError, match="eip7002"):
        verify_provenance(manifest)


def test_module_write_is_rename_atomic(tmp_path, monkeypatch):
    """E12xx-era regression: the emitter used to bare-write compiled
    modules to their FINAL path — a crash mid-``make pyspec`` left a
    torn module that ``make lint``'s ``test -d compiled`` guard never
    rebuilt, and a module truncated at a statement boundary is still
    valid python (silently inheriting the previous fork's bodies).
    The write must be rename-atomic: a failed write leaves the OLD
    content intact and no stray temp file the next reader trusts."""
    import pytest
    from consensus_specs_tpu.compiler.emit import _write_module
    out = tmp_path / "mod.py"
    out.write_text("OLD = 1\n")
    _write_module(str(out), "NEW = 2\n")
    assert out.read_text() == "NEW = 2\n"         # the happy path lands
    real_replace = os.replace

    def crash(src, dst):
        raise OSError("simulated crash at rename")

    monkeypatch.setattr(os, "replace", crash)
    with pytest.raises(OSError):
        _write_module(str(out), "TORN = 3\n")
    monkeypatch.setattr(os, "replace", real_replace)
    assert out.read_text() == "NEW = 2\n"         # never torn
    assert [p.name for p in tmp_path.iterdir()
            if p.name.endswith(".tmp")] == []     # temp cleaned up


def test_spec_doc_write_is_rename_atomic(tmp_path, monkeypatch):
    """Same discipline for the regenerated markdown (the compiler's
    SOURCE of truth): a crash mid-``mdgen`` must leave the old doc."""
    import pytest
    from consensus_specs_tpu.compiler.mdgen import _write_doc
    out = tmp_path / "specs" / "demo.md"
    out.parent.mkdir()
    out.write_text("# old\n")

    def crash(src, dst):
        raise OSError("simulated crash at rename")

    monkeypatch.setattr(os, "replace", crash)
    with pytest.raises(OSError):
        _write_doc(str(out), "# new\n")
    assert out.read_text() == "# old\n"
