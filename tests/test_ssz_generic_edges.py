"""SSZ wire-format edge cases (the ssz_generic vector family).

Reference model: ``tests/generators/ssz_generic/`` hand-built edge cases
against ``ssz/simple-serialize.md``: uint boundaries, bitlist delimiters,
offset validation, union selectors, nested variable-size layouts.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.utils.ssz import (
    serialize, deserialize, hash_tree_root, uint8, uint16, uint32, uint64, uint128, uint256, Bitlist, Bitvector, ByteList, ByteVector, Vector, List, Container, Union, Bytes32)


@pytest.mark.parametrize("typ,bits", [
    (uint8, 8), (uint16, 16), (uint32, 32), (uint64, 64),
    (uint128, 128), (uint256, 256)])
def test_uint_boundaries(typ, bits):
    top = 2**bits - 1
    assert serialize(typ(top)) == b"\xff" * (bits // 8)
    assert deserialize(typ, b"\xff" * (bits // 8)) == top
    with pytest.raises(ValueError):
        typ(top + 1)
    with pytest.raises(ValueError):
        typ(-1)
    # round trip at a non-trivial value
    v = typ(top // 3)
    assert deserialize(typ, serialize(v)) == v


def test_uint_serialization_is_little_endian():
    assert serialize(uint32(0x01020304)) == b"\x04\x03\x02\x01"
    assert serialize(uint16(0x0102)) == b"\x02\x01"


@pytest.mark.parametrize("n_bits", [0, 1, 7, 8, 9, 255, 256, 300])
def test_bitlist_delimiter_roundtrip(n_bits):
    T = Bitlist[512]
    value = T([i % 2 == 0 for i in range(n_bits)])
    data = serialize(value)
    # delimiter bit: serialization is never empty and last byte non-zero
    assert len(data) >= 1 and data[-1] != 0
    assert deserialize(T, data) == value


def test_bitlist_rejects_missing_delimiter():
    with pytest.raises(ValueError):
        deserialize(Bitlist[16], b"")
    with pytest.raises(ValueError):
        deserialize(Bitlist[16], b"\x01\x00")  # trailing zero byte


def test_bitlist_rejects_overflow_bits():
    # 9 content bits into a limit-8 bitlist
    data = serialize(Bitlist[16]([True] * 9))
    with pytest.raises(ValueError):
        deserialize(Bitlist[8], data)


def test_bitvector_rejects_nonzero_padding():
    data = serialize(Bitvector[4]([True, True, True, True]))
    assert data == b"\x0f"
    with pytest.raises(ValueError):
        Bitvector[4].decode_bytes(b"\x1f")  # bit 4 set beyond length


class _VarElem(Container):
    data: ByteList[64]


class _VarOuter(Container):
    fixed: uint64
    var_a: List[uint16, 16]
    var_b: _VarElem


def test_container_offset_layout():
    value = _VarOuter(fixed=7, var_a=[1, 2, 3], var_b=_VarElem(data=b"zz"))
    data = serialize(value)
    # fixed part: uint64 + two 4-byte offsets
    assert int.from_bytes(data[8:12], "little") == 16  # first offset
    rt = deserialize(_VarOuter, data)
    assert rt == value
    assert hash_tree_root(rt) == hash_tree_root(value)


def test_container_rejects_bad_first_offset():
    value = _VarOuter(fixed=7, var_a=[1], var_b=_VarElem(data=b"q"))
    data = bytearray(serialize(value))
    data[8:12] = (17).to_bytes(4, "little")  # first offset != fixed size
    with pytest.raises(ValueError):
        deserialize(_VarOuter, bytes(data))


def test_container_rejects_decreasing_offsets():
    value = _VarOuter(fixed=7, var_a=[1, 2], var_b=_VarElem(data=b"q"))
    data = bytearray(serialize(value))
    # second offset less than the first
    data[12:16] = (10).to_bytes(4, "little")
    with pytest.raises(ValueError):
        deserialize(_VarOuter, bytes(data))


def test_union_selector_edges():
    U = Union[None, uint64, Bytes32]
    assert serialize(U(0)) == b"\x00"
    two = U(2, b"\x11" * 32)
    assert serialize(two)[0] == 2
    assert deserialize(U, serialize(two)) == two
    with pytest.raises(ValueError):
        deserialize(U, b"\x03\x00")  # selector out of range
    with pytest.raises(ValueError):
        deserialize(U, b"\x00\x00")  # None option with payload


def test_empty_collections_roots_are_distinct_by_type():
    assert hash_tree_root(List[uint64, 16]()) != \
        hash_tree_root(List[uint64, 32]())
    # limits under one 256-bit chunk share a tree depth; crossing the
    # chunk boundary must change the (empty) root
    assert hash_tree_root(Bitlist[16]()) == hash_tree_root(Bitlist[256]())
    assert hash_tree_root(Bitlist[16]()) != hash_tree_root(Bitlist[512]())


def test_vector_of_containers_roundtrip():
    class Pair(Container):
        a: uint8
        b: uint8
    T = Vector[Pair, 3]
    v = T([Pair(a=i, b=i + 1) for i in range(3)])
    assert deserialize(T, serialize(v)) == v
    with pytest.raises(ValueError):
        deserialize(T, serialize(v)[:-1])  # truncated


def test_bytelist_limit_enforced():
    with pytest.raises(ValueError):
        ByteList[4](b"12345")
    assert deserialize(ByteList[4], b"1234") == ByteList[4](b"1234")
    with pytest.raises(ValueError):
        deserialize(ByteList[4], b"12345")


def test_bytevector_exact_length():
    assert len(ByteVector[5](b"abcde")) == 5
    with pytest.raises(ValueError):
        ByteVector[5](b"abcd")
