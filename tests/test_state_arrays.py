"""Unit + differential suite for the copy-on-write columnar state store
(``consensus_specs_tpu/state/arrays.py``).

Covers the store's four contracts:

* **structural freshness** — columns revalidate against the SSZ
  sequences' mutation generations; any write through the sequence API
  (including nested container fields) is seen by the next read, with
  the store enabled AND disabled;
* **copy-on-write snapshot/fork** — forked states share column arrays
  until one side writes, replays forked from one base produce
  byte-identical roots vs independent copies, and the copy census stays
  far below columns x replays;
* **one commit per epoch transition** — inside ``commit_scope`` the
  balance-family writes hit SSZ once, spec-loop fallbacks flush first,
  and an exception discards pending writes;
* **shared columns** — the hash-forest bulk container-root build reads
  the store's committed registry columns (provider direction) and the
  store adopts a forest extraction (stash direction).
"""
from random import Random

import numpy as np
import pytest

from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.ops import epoch_kernels as ek
from consensus_specs_tpu.ops import att_prep
from consensus_specs_tpu.state import arrays
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.test_infra.genesis import create_genesis_state
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import hash_tree_root
from consensus_specs_tpu.utils.ssz.forest import hash_forest

N_VALIDATORS = 64


@pytest.fixture(autouse=True)
def _mode_reset():
    prev_bls = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev_bls
    ek.use_auto()
    arrays.use_auto()


def _spec(fork="altair"):
    return build_spec(fork, "minimal")


def _genesis(spec):
    return create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * N_VALIDATORS,
        spec.MAX_EFFECTIVE_BALANCE)


# ---------------------------------------------------------------------------
# extraction, caching, structural invalidation
# ---------------------------------------------------------------------------

def test_registry_extracted_once_then_hits():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    with counting() as delta:
        a = arrays.of(state).registry()
        b = arrays.of(state).registry()
    assert a is b
    assert delta["cache.miss{cache=state_arrays}"] == 1
    assert delta["cache.hit{cache=state_arrays}"] == 1
    # one extraction event total (python pass OR forest-stash adoption)
    assert delta["state_arrays.extracts{column=registry}"] \
        + delta["state_arrays.adoptions"] == 1


@pytest.mark.parametrize("engine_on", [True, False])
def test_ssz_sequence_mutation_invalidates(engine_on):
    """Columns revalidate against the sequence mutation generation: a
    write through the SSZ API (nested container field included) is seen
    by the very next read — no root hashing, no cache keys."""
    spec = _spec()
    state = _genesis(spec)
    (arrays.use_arrays if engine_on else arrays.use_fallback)()
    cols = arrays.registry_of(state)
    assert int(cols["eff"][3]) == int(spec.MAX_EFFECTIVE_BALANCE)
    state.validators[3].effective_balance = 17 * 10**9
    cols2 = arrays.registry_of(state)
    assert int(cols2["eff"][3]) == 17 * 10**9
    state.balances[5] = 123
    assert int(arrays.of(state).balances()[5]) == 123


def test_wholesale_field_replacement_invalidates():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    assert int(sa.balances()[0]) == int(spec.MAX_EFFECTIVE_BALANCE)
    state.balances = [7] * N_VALIDATORS      # new sequence object
    assert int(arrays.of(state).balances()[0]) == 7


def test_disabled_store_is_detached():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_fallback()
    s1, s2 = arrays.of(state), arrays.of(state)
    assert s1 is not s2
    assert arrays.backend_name() == "fallback"
    arrays.use_arrays()
    assert arrays.of(state) is arrays.of(state)
    assert arrays.backend_name() == "state_arrays"


def test_env_flag_disables_auto(monkeypatch):
    spec = _spec()
    state = _genesis(spec)
    monkeypatch.setenv("CS_TPU_STATE_ARRAYS", "0")
    arrays.use_auto()
    assert not arrays.enabled()
    assert arrays.of(state) is not arrays.of(state)
    # live re-read: flipping the variable after import works too
    monkeypatch.setenv("CS_TPU_STATE_ARRAYS", "1")
    assert arrays.enabled()
    assert arrays.of(state) is arrays.of(state)


# ---------------------------------------------------------------------------
# deferred commits
# ---------------------------------------------------------------------------

def test_commit_scope_defers_to_one_commit():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    base = sa.balances()
    with counting() as delta:
        with arrays.commit_scope(state):
            sa.set_balances(base + np.uint64(1))
            # SSZ must still hold the old values mid-scope
            assert int(state.balances[0]) == int(base[0])
            sa.set_balances(sa.balances() + np.uint64(1))
            assert delta["state_arrays.commits"] == 0
        assert int(state.balances[0]) == int(base[0]) + 2
    assert delta["state_arrays.commits"] == 1


def test_commit_scope_discards_on_exception():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    before = int(state.balances[0])
    with pytest.raises(ValueError, match="boom"):
        with arrays.commit_scope(state):
            sa.set_balances(sa.balances() + np.uint64(9))
            raise ValueError("boom")
    assert int(state.balances[0]) == before
    # pending write discarded: the store agrees with SSZ again
    assert int(arrays.of(state).balances()[0]) == before


def test_deferred_conflict_raises():
    """A direct SSZ write racing a pending deferred column write is a
    protocol violation — fail loud, never clobber silently."""
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    with pytest.raises(RuntimeError, match="deferred"):
        with arrays.commit_scope(state):
            sa.set_balances(sa.balances() + np.uint64(1))
            state.balances[0] = 42       # bypasses the store


def test_deferred_conflict_raises_on_read():
    """Same protocol violation, but a column READ lands between the
    direct SSZ write and scope exit: the revalidating read must raise,
    not quietly re-extract and drop the pending engine write."""
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    with pytest.raises(RuntimeError, match="deferred"):
        with arrays.commit_scope(state):
            sa.set_balances(sa.balances() + np.uint64(7))
            state.balances[0] = 42       # bypasses the store
            sa.balances()                # revalidates -> must fail loud


def test_flush_commits_pending_mid_scope():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    with arrays.commit_scope(state):
        sa.set_balances(sa.balances() + np.uint64(5))
        arrays.flush(state)
        # the spec-loop fallback path sees fresh SSZ
        assert int(state.balances[0]) \
            == int(spec.MAX_EFFECTIVE_BALANCE) + 5


# ---------------------------------------------------------------------------
# copy-on-write fork
# ---------------------------------------------------------------------------

def test_fork_shares_columns_until_write():
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    base_cols = arrays.registry_of(state)
    with counting() as delta:
        forked = arrays.fork_state(state)
        fcols = arrays.of(forked).registry()
    assert fcols is base_cols                      # shared, no copy
    assert delta["state_arrays.forks"] == 1
    assert delta["state_arrays.extracts{column=registry}"] == 0
    assert delta["cache.miss{cache=state_arrays}"] == 0
    with counting() as delta:
        w = arrays.of(forked).registry_writable()
    assert delta["state_arrays.cow_copies"] == 1
    assert w is not base_cols
    w["eff"][0] = np.uint64(1)
    assert int(base_cols["eff"][0]) == int(spec.MAX_EFFECTIVE_BALANCE)


def test_concurrent_replays_byte_identical_and_shared():
    """16 replays forked from one base snapshot: byte-identical roots
    vs independent full-copy replays, while the copy-on-write census
    stays far below columns x replays."""
    spec = _spec("altair")
    state = _genesis(spec)
    ek.use_loops()
    for _ in range(3):
        next_epoch(spec, state)
    ek.use_auto()
    arrays.use_arrays()
    arrays.registry_of(state)        # warm the base columns
    arrays.of(state).balances()
    base_root = bytes(hash_tree_root(state))
    rng = Random(7)
    # halving a balance forces a hysteresis crossing, so each replay's
    # effective-balance update takes the registry copy-on-write path
    perturbs = [(rng.randrange(N_VALIDATORS),
                 int(spec.MAX_EFFECTIVE_BALANCE) // 2 + rng.randrange(100))
                for _ in range(16)]

    def replay(st, i, amount):
        st.balances[i] = amount
        next_epoch(spec, st)
        return bytes(hash_tree_root(st))

    with counting() as delta:
        forked_roots = [replay(arrays.fork_state(state), i, amt)
                        for i, amt in perturbs]
    n_columns = len(arrays._COLUMNS)
    assert delta["state_arrays.forks"] == 16
    assert 0 < delta["state_arrays.cow_copies"] < n_columns * 16
    # and the forks never re-extracted the shared registry
    assert delta["state_arrays.extracts{column=registry}"] == 0

    # independent leg with the store OFF: a genuine differential
    # oracle — a store bug corrupting a shared column cannot cancel
    # out of both sides of the comparison
    arrays.use_fallback()
    independent_roots = [replay(state.copy(), i, amt)
                         for i, amt in perturbs]
    arrays.use_arrays()
    assert forked_roots == independent_roots
    # the base state itself is untouched by any replay
    assert bytes(hash_tree_root(state)) == base_root


def test_plain_copy_carries_columns_and_pending_writes():
    """Regressions from review: (a) every plain ``state.copy()`` of a
    store-carrying state shares the columns copy-on-write (fork-choice
    block/checkpoint states are made with ``.copy()``, not
    ``fork_state``); (b) a copy taken inside a commit scope flushes the
    pending column writes BEFORE the field snapshot — a copy that
    missed them would silently diverge from its own store."""
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    cols = sa.registry()
    with counting() as delta:
        c = state.copy()
        assert arrays.of(c).registry() is cols
    assert delta["state_arrays.forks"] == 1
    assert delta["state_arrays.extracts{column=registry}"] == 0

    with arrays.commit_scope(state):
        sa.set_balances(sa.balances() + np.uint64(7))
        c2 = state.copy()
    assert int(c2.balances[0]) == int(spec.MAX_EFFECTIVE_BALANCE) + 7
    assert int(arrays.of(c2).balances()[0]) == int(c2.balances[0])
    assert bytes(hash_tree_root(c2)) == bytes(hash_tree_root(state))


def test_disabled_copy_of_store_carrying_state_shares_nothing():
    """Regression: a ``state.copy()`` taken AFTER the store is disabled
    (the differential-oracle shape: warm a store, then use_fallback for
    the independent leg) must share nothing with the parent — no
    attached store, no cells, and no forest column-provider binding on
    the copy's sequences.  Shared columns would let a store bug cancel
    out of both sides of a forked-vs-independent root comparison."""
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    arrays.registry_of(state)            # warm + bind the parent
    arrays.use_fallback()
    c = state.copy()
    assert c.__dict__.get("_state_arrays") is None
    assert arrays.peek_registry(c.validators) is None
    # the parent's own binding is untouched
    arrays.use_arrays()
    assert arrays.peek_registry(state.validators) is not None


def test_forest_provider_columns_merkleize():
    """Regression from review: the provider hands the forest strided
    structured-array field views; the columnar root build must accept
    them (ascontiguousarray) — a fresh full merkleization with a warm
    registry cell used to crash."""
    spec = build_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 512, spec.MAX_EFFECTIVE_BALANCE)
    arrays.use_arrays()
    oracle = bytes(
        type(state.validators).decode_bytes(state.validators.serialize())
        .hash_tree_root())
    arrays.registry_of(state)                    # warm + bind provider
    assert arrays.peek_registry(state.validators) is not None
    fresh = state.copy().validators              # cold tree, warm provider
    object.__setattr__(fresh, "_root_memo", None)
    object.__setattr__(fresh, "_tree", None)
    assert bytes(hash_tree_root(fresh)) == oracle


def test_fork_drops_stale_cells():
    """Regression: forking a store whose cell went stale (the parent
    sequence mutated after extraction) must DROP the cell — rebinding
    it under the child's fresh generation would launder stale data into
    a valid-looking column and diverge the forked replay."""
    spec = _spec()
    state = _genesis(spec)
    arrays.use_arrays()
    sa = arrays.of(state)
    sa.balances()                       # warm the cell...
    state.balances[7] = 1234            # ...then go stale behind it
    forked = arrays.fork_state(state)
    assert int(arrays.of(forked).balances()[7]) == 1234
    # same for the registry cell
    arrays.registry_of(state)
    state.validators[7].effective_balance = 5 * 10**9
    forked2 = arrays.fork_state(state)
    assert int(arrays.of(forked2).registry()["eff"][7]) == 5 * 10**9


# ---------------------------------------------------------------------------
# engine integration: one extraction per epoch, fallback flush
# ---------------------------------------------------------------------------

def test_one_registry_extraction_per_epoch_replay():
    """A multi-epoch replay extracts registry columns at most once per
    epoch transition (here: once TOTAL — empty blocks never mutate the
    registry, so the lineage-attached columns stay valid throughout)."""
    spec = _spec("altair")
    state = _genesis(spec)
    arrays.use_arrays()
    ek.use_vectorized()
    next_epoch(spec, state)       # genesis-epoch transition is a no-op
    with counting() as delta:
        for _ in range(3):
            next_epoch(spec, state)
    assert delta["state_arrays.extracts{column=registry}"] \
        + delta["state_arrays.adoptions"] <= 3
    assert delta["epoch.transition{path=vectorized}"] > 0
    assert delta["epoch.fallbacks{reason=guard}"] == 0
    # balance-family commits: exactly one per epoch transition
    assert delta["state_arrays.commits"] == 3


def test_process_epoch_differential_arrays_on_off():
    """Full process_slots epoch transitions must commit byte-identical
    post-states with the store attached and detached, vectorized engine
    on and off — the 2x2 matrix."""
    spec = _spec("deneb")
    state = _genesis(spec)
    ek.use_loops()
    for _ in range(2):
        next_epoch(spec, state)
    rng = Random(11)
    for i in range(N_VALIDATORS):
        state.previous_epoch_participation[i] = \
            spec.ParticipationFlags(rng.randint(0, 7))
        state.current_epoch_participation[i] = \
            spec.ParticipationFlags(rng.randint(0, 7))
        state.inactivity_scores[i] = rng.randint(0, 40)
    roots = {}
    for arrays_mode, ek_mode in (("on", "on"), ("on", "off"),
                                 ("off", "on"), ("off", "off")):
        (arrays.use_arrays if arrays_mode == "on"
         else arrays.use_fallback)()
        (ek.use_vectorized if ek_mode == "on" else ek.use_loops)()
        st = state.copy()
        next_epoch(spec, st)
        roots[(arrays_mode, ek_mode)] = bytes(hash_tree_root(st))
    assert len(set(roots.values())) == 1, roots


def test_guard_fallback_flushes_pending_writes():
    """Inside a deferred epoch scope, a guard trip must flush the
    pending column writes BEFORE the spec loop reads SSZ — the
    fallback-path state must equal the all-loops state exactly."""
    spec = _spec("altair")
    state = _genesis(spec)
    ek.use_loops()
    for _ in range(3):
        next_epoch(spec, state)
    # trips the rewards guard (eff * score can overflow a uint64 lane)
    # AFTER process_inactivity_updates already wrote deferred scores
    state.inactivity_scores[3] = 10**9
    rng = Random(13)
    for i in range(N_VALIDATORS):
        state.previous_epoch_participation[i] = \
            spec.ParticipationFlags(rng.randint(0, 7))
    s_loop, s_vec = state.copy(), state.copy()
    next_epoch(spec, s_loop)
    ek.use_vectorized()
    arrays.use_arrays()
    with counting() as delta:
        next_epoch(spec, s_vec)
    assert delta["epoch.fallbacks{reason=guard}"] >= 1
    assert bytes(hash_tree_root(s_loop)) == bytes(hash_tree_root(s_vec))


# ---------------------------------------------------------------------------
# forest column sharing
# ---------------------------------------------------------------------------

def test_forest_reads_store_columns():
    """With a live store, the bulk container-root build consumes the
    committed registry columns through the provider instead of its own
    python walk — and the root matches the no-cache oracle."""
    spec = build_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * 512, spec.MAX_EFFECTIVE_BALANCE)
    arrays.use_arrays()
    cols = arrays.registry_of(state)
    provided = arrays.peek_registry(state.validators)
    assert provided is not None
    assert provided["effective_balance"] is not None
    assert int(provided["slashed"][0]) == 0
    with hash_forest():
        root = hash_tree_root(state)
    oracle = type(state).decode_bytes(state.serialize()).hash_tree_root()
    assert bytes(root) == bytes(oracle)
    # provider goes stale with the sequence generation
    state.validators[0].slashed = True
    assert arrays.peek_registry(state.validators) is None
    assert bool(arrays.registry_of(state)["sl"][0])
    assert arrays.peek_registry(state.validators) is not None
    assert int(cols["sl"][0]) == 0       # old snapshot untouched


# ---------------------------------------------------------------------------
# attestation message preparation (ops/att_prep.py)
# ---------------------------------------------------------------------------

def _fake_attestations(spec, state, n, rng):
    atts = []
    for _ in range(n):
        data = spec.AttestationData(
            slot=rng.randrange(64), index=rng.randrange(4),
            beacon_block_root=rng.randbytes(32),
            source=spec.Checkpoint(epoch=rng.randrange(8),
                                   root=rng.randbytes(32)),
            target=spec.Checkpoint(epoch=rng.randrange(8),
                                   root=rng.randbytes(32)))
        atts.append(spec.Attestation(data=data))
    return atts


def test_att_prep_roots_match_spec():
    """The batched checkpoint/data/signing roots must equal the
    per-object spec computations bit for bit, and the poked memos must
    survive value-semantics copies."""
    spec = _spec("altair")
    state = _genesis(spec)
    rng = Random(17)
    atts = _fake_attestations(spec, state, 9, rng)
    oracles = []
    for a in atts:
        fresh = type(a.data).decode_bytes(a.data.serialize())
        domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                                 a.data.target.epoch)
        oracles.append((bytes(fresh.hash_tree_root()),
                        bytes(spec.compute_signing_root(fresh, domain))))
    with counting() as delta:
        att_prep.prepare_block_attestations(spec, state, atts)
    assert delta["att_prep.prepared"] == 9
    for a, (data_root, signing_root) in zip(atts, oracles):
        assert bytes(hash_tree_root(a.data)) == data_root
        hit = att_prep.lookup_signing_root(state, a.data)
        assert hit == signing_root
        # value-semantics copy (the get_indexed_attestation path)
        copied = spec.IndexedAttestation(data=a.data)
        assert bytes(hash_tree_root(copied.data)) == data_root
    assert att_prep.lookup_signing_root(
        state, _fake_attestations(spec, state, 1, rng)[0].data) is None


def test_att_prep_skips_extended_attestation_data_layouts():
    """Regression: the legacy sharding lineage appends
    ``shard_transition_root`` to ``AttestationData``.  The 5-field
    chunk cube would compute (and memo-poke) wrong container roots for
    that layout — preparation must decline, leaving every lookup to
    miss into the spec body with UNPOISONED root memos."""
    spec = _spec("sharding")
    state = _genesis(spec)
    rng = Random(23)
    atts = _fake_attestations(spec, state, 3, rng)
    assert "shard_transition_root" in type(atts[0].data)._fields
    oracles = [bytes(type(a.data).decode_bytes(
        a.data.serialize()).hash_tree_root()) for a in atts]
    with counting() as delta:
        att_prep.prepare_block_attestations(spec, state, atts)
    assert delta["att_prep.prepared"] == 0
    for a, data_root in zip(atts, oracles):
        assert att_prep.lookup_signing_root(state, a.data) is None
        assert bytes(hash_tree_root(a.data)) == data_root


def test_att_prep_wrapper_hits_through_block_processing():
    """Processing a real block's attestations must route every
    is_valid_indexed_attestation through the prepared table."""
    from consensus_specs_tpu.test_infra.attestations import (
        next_epoch_with_attestations)
    spec = build_spec("phase0", "minimal")
    state = create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * N_VALIDATORS,
        spec.MAX_EFFECTIVE_BALANCE)
    ek.use_loops()
    next_epoch(spec, state)
    with counting() as delta:
        _, _, state = next_epoch_with_attestations(spec, state, True, False)
    assert delta["att_prep.blocks"] > 0
    assert delta["att_prep.prepared"] > 0
    assert delta["att_prep.hits"] == delta["att_prep.prepared"]
    assert delta["att_prep.misses"] == 0
