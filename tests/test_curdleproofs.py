"""Curdleproofs-style shuffle argument: completeness, soundness
negatives (including the padding-lane deletion forgery), and wire-format
properties.  Reference role: the external ``curdleproofs`` package the
reference's whisk spec delegates to (reference ``setup.py:555``)."""
import pytest

from consensus_specs_tpu.ops.bls12_381.fields import R_ORDER
from consensus_specs_tpu.ops.bls12_381.curve import G1_GENERATOR
from consensus_specs_tpu.ops import curdleproofs as cp


def _instance(n, k=77, sigma=None, seed=3):
    sigma = sigma if sigma is not None else list(range(n))[::-1]
    R = [G1_GENERATOR.mult(seed + 2 * i + 1) for i in range(n)]
    S = [G1_GENERATOR.mult(7 * seed + 3 * i + 2) for i in range(n)]
    T = [R[sigma[i]].mult(k) for i in range(n)]
    U = [S[sigma[i]].mult(k) for i in range(n)]
    return R, S, T, U, sigma, k


def _det_rng():
    state = [123456789]

    def rng():
        state[0] = (state[0] * 6364136223846793005 + 1442695040888963407) \
            % 2**64
        return state[0] % (R_ORDER - 1) + 1
    return rng


def test_roundtrip_power_of_two():
    R, S, T, U, sigma, k = _instance(4)
    proof = cp.prove_shuffle(R, S, T, U, sigma, k, rng=_det_rng())
    assert cp.verify_shuffle(R, S, T, U, proof)


def test_roundtrip_padded():
    # n=3 pads to N=4: exercises the padding-pin lanes
    R, S, T, U, sigma, k = _instance(3, sigma=[1, 2, 0])
    proof = cp.prove_shuffle(R, S, T, U, sigma, k, rng=_det_rng())
    assert cp.verify_shuffle(R, S, T, U, proof)


def test_compressed_bytes_inputs():
    R, S, T, U, sigma, k = _instance(4)
    proof = cp.prove_shuffle(R, S, T, U, sigma, k, rng=_det_rng())
    as_bytes = [[p.to_compressed() for p in col] for col in (R, S, T, U)]
    assert cp.verify_shuffle(*as_bytes, proof)


def test_rejects_wrong_instance():
    R, S, T, U, sigma, k = _instance(4)
    proof = cp.prove_shuffle(R, S, T, U, sigma, k, rng=_det_rng())
    # different scalar on one output tracker
    T_bad = list(T)
    T_bad[0] = T[0] + G1_GENERATOR
    assert not cp.verify_shuffle(R, S, T_bad, U, proof)
    # swapped outputs (post is no longer THIS permutation+scalar image)
    assert not cp.verify_shuffle(R, S, [T[1], T[0]] + T[2:],
                                 [U[1], U[0]] + U[2:], proof)


def test_rejects_tampered_proof():
    R, S, T, U, sigma, k = _instance(4)
    proof = cp.prove_shuffle(R, S, T, U, sigma, k, rng=_det_rng())
    for off in (0, 48 * 2 + 5, len(proof) - 1):
        bad = bytearray(proof)
        bad[off] ^= 0x01
        assert not cp.verify_shuffle(R, S, T, U, bytes(bad))
    assert not cp.verify_shuffle(R, S, T, U, proof[:-32])


def test_rejects_padding_lane_forgery():
    """Regression: a prover that parks an a-power in a padding lane
    (deleting a tracker whose padded R/S are infinity) must be caught by
    the Z-vector padding pin."""
    from consensus_specs_tpu.ops.curdleproofs import (
        CRS, _instance_transcript, _pad, _pad_pin_bases,
        _prove_grand_product, _prove_same_msm, msm)

    n, k = 3, 77
    R, S, T, U, _sigma, k = _instance(n, k=k)
    # forged instance: tracker 0's image is destroyed (infinity)
    from consensus_specs_tpu.ops.bls12_381.curve import G1Point
    T_f = [G1Point.inf()] + [R[i].mult(k) for i in (1, 2)]
    U_f = [G1Point.inf()] + [S[i].mult(k) for i in (1, 2)]

    rng = _det_rng()
    crs = CRS.get(max(n, 2))
    N = crs.size
    t = _instance_transcript(R, S, T_f, U_f)
    a = t.challenge(b"a")
    a_pow = [pow(a, i + 1, R_ORDER) for i in range(n)]
    # dishonest b: a^1 parked in the padding lane (index 3), so that the
    # grand product still sees the full power multiset
    b = [0] * N
    b[1], b[2] = a_pow[1], a_pow[2]   # honest lanes for trackers 1, 2
    b[3] = a_pow[0]                   # tracker 0's power -> padding lane
    r_B = rng()
    B = msm(crs.G_vec, b) + crs.H_blind.mult(r_B)
    t.absorb_points(b"B", [B])
    beta = t.challenge(b"beta")
    Rp, Sp = _pad(list(R), N), _pad(list(S), N)
    V_R, V_S = msm(Rp, b), msm(Sp, b)
    t.absorb_points(b"V", [V_R, V_S])
    c = [(bj + beta) % R_ORDER for bj in b]
    prod = 1
    for ai in a_pow:
        prod = prod * (ai + beta) % R_ORDER
    prod = prod * pow(beta, N - n, R_ORDER) % R_ORDER
    gp = _prove_grand_product(t, crs, c, r_B, prod, rng)
    smsm = _prove_same_msm(t, crs, Rp, Sp, _pad_pin_bases(crs, n),
                           b, r_B, rng)
    w = rng()
    W_R, W_S = V_R.mult(w), V_S.mult(w)
    t.absorb_points(b"dleq/W", [W_R, W_S])
    ch = t.challenge(b"dleq/c")
    s_k = (w + ch * k) % R_ORDER
    forged = cp._serialize(n, B, V_R, V_S, gp, smsm, (W_R, W_S, s_k))
    assert not cp.verify_shuffle(R, S, T_f, U_f, forged)


def test_proof_size_is_permutation_independent():
    R, S, T, U, sigma, k = _instance(4, sigma=[3, 1, 0, 2])
    p1 = cp.prove_shuffle(R, S, T, U, sigma, k, rng=_det_rng())
    R2, S2, T2, U2, sigma2, k2 = _instance(4, sigma=[0, 1, 2, 3], k=5)
    p2 = cp.prove_shuffle(R2, S2, T2, U2, sigma2, k2, rng=_det_rng())
    assert len(p1) == len(p2)
    # and the permutation bytes appear nowhere (ZK is structural: only
    # commitments, fold points and masked scalars are on the wire)
    assert cp.verify_shuffle(R2, S2, T2, U2, p2)
    assert not cp.verify_shuffle(R, S, T, U, p2)


from consensus_specs_tpu.utils.env_flags import HEAVY


@pytest.mark.parametrize("n", [2] + ([5] if HEAVY else []))
def test_various_sizes(n):
    R, S, T, U, sigma, k = _instance(
        n, sigma=list(range(1, n)) + [0], k=1234567)
    proof = cp.prove_shuffle(R, S, T, U, sigma, k, rng=_det_rng())
    assert cp.verify_shuffle(R, S, T, U, proof)
