"""Shard rotation accessors.

Reference model: ``test/sharding/unittests/test_get_start_shard.py`` —
the surviving executable contract of the sharding feature
(``get_committee_count_delta`` / ``get_start_shard`` /
``current_epoch_start_shard``; see ``forks/sharding.py`` lineage note).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)
from consensus_specs_tpu.test_infra.block import next_epoch


@with_phases(["sharding"])
@spec_state_test
def test_get_committee_count_delta(spec, state):
    assert spec.get_committee_count_delta(state, 0, 0) == 0
    assert spec.get_committee_count_per_slot(state, 0) != 0
    assert spec.get_committee_count_delta(state, 0, 1) == \
        spec.get_committee_count_per_slot(state, 0)
    assert spec.get_committee_count_delta(state, 1, 2) == \
        spec.get_committee_count_per_slot(state, 0)
    assert spec.get_committee_count_delta(state, 0, 2) == \
        spec.get_committee_count_per_slot(state, 0) * 2
    assert spec.get_committee_count_delta(state, 0, spec.SLOTS_PER_EPOCH) == \
        spec.get_committee_count_per_slot(state, 0) * spec.SLOTS_PER_EPOCH
    assert spec.get_committee_count_delta(
        state, 0, 2 * spec.SLOTS_PER_EPOCH) == (
        spec.get_committee_count_per_slot(state, 0) * spec.SLOTS_PER_EPOCH
        + spec.get_committee_count_per_slot(state, 1) * spec.SLOTS_PER_EPOCH)


@with_phases(["sharding"])
@spec_state_test
def test_get_start_shard_current_epoch_start(spec, state):
    assert state.current_epoch_start_shard == 0
    next_epoch(spec, state)
    active_shard_count = spec.get_active_shard_count(state)
    assert state.current_epoch_start_shard == (
        spec.get_committee_count_delta(state, 0, spec.SLOTS_PER_EPOCH)
        % active_shard_count)
    current_epoch_start_slot = spec.compute_start_slot_at_epoch(
        spec.get_current_epoch(state))
    assert spec.get_start_shard(state, current_epoch_start_slot) == \
        state.current_epoch_start_shard


@with_phases(["sharding"])
@spec_state_test
def test_get_start_shard_next_slot(spec, state):
    next_epoch(spec, state)
    active_shard_count = spec.get_active_shard_count(state)
    current_epoch_start_slot = spec.compute_start_slot_at_epoch(
        spec.get_current_epoch(state))
    slot = current_epoch_start_slot + 1
    start_shard = spec.get_start_shard(state, slot)
    expected = (
        state.current_epoch_start_shard
        + spec.get_committee_count_delta(state, current_epoch_start_slot, slot)
    ) % active_shard_count
    assert start_shard == expected


@with_phases(["sharding"])
@spec_state_test
def test_get_start_shard_previous_slot(spec, state):
    next_epoch(spec, state)
    active_shard_count = spec.get_active_shard_count(state)
    current_epoch_start_slot = spec.compute_start_slot_at_epoch(
        spec.get_current_epoch(state))
    slot = current_epoch_start_slot - 1
    start_shard = spec.get_start_shard(state, slot)
    expected = (
        state.current_epoch_start_shard
        + spec.MAX_COMMITTEES_PER_SLOT * spec.SLOTS_PER_EPOCH
        * active_shard_count
        - spec.get_committee_count_delta(
            state, slot, current_epoch_start_slot)
    ) % active_shard_count
    assert start_shard == expected


@with_phases(["sharding"])
@spec_state_test
def test_get_start_shard_far_past_epoch(spec, state):
    initial_epoch = spec.get_current_epoch(state)
    initial_start_slot = spec.compute_start_slot_at_epoch(initial_epoch)
    initial_start_shard = state.current_epoch_start_shard
    for _ in range(spec.MAX_SHARDS + 2):
        next_epoch(spec, state)
    assert spec.get_start_shard(state, initial_start_slot) == \
        initial_start_shard
