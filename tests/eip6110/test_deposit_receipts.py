"""EIP-6110 deposit-receipt tests.

Reference model: ``test/eip6110/block_processing/test_deposit_receipt.py``
against ``specs/_features/eip6110/beacon-chain.md:194-232``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, always_bls)
from consensus_specs_tpu.test_infra.deposits import build_deposit_data
from consensus_specs_tpu.test_infra.keys import pubkeys, privkeys
from consensus_specs_tpu.utils.hash_function import hash


def _receipt(spec, validator_index, amount, index=0, signed=True):
    pubkey = pubkeys[validator_index]
    withdrawal_credentials = spec.BLS_WITHDRAWAL_PREFIX + hash(pubkey)[1:]
    data = build_deposit_data(spec, pubkey, privkeys[validator_index],
                              amount, withdrawal_credentials, signed=signed)
    return spec.DepositReceipt(
        pubkey=data.pubkey,
        withdrawal_credentials=data.withdrawal_credentials,
        amount=data.amount,
        signature=data.signature,
        index=index,
    )


@with_phases(["eip6110"])
@spec_state_test
def test_genesis_start_index_unset(spec, state):
    assert state.deposit_receipts_start_index == \
        spec.UNSET_DEPOSIT_RECEIPTS_START_INDEX


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_new_validator_from_receipt(spec, state):
    pre_count = len(state.validators)
    new_index = pre_count
    receipt = _receipt(spec, new_index, spec.MAX_EFFECTIVE_BALANCE, index=7)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert len(state.validators) == pre_count + 1
    assert state.balances[new_index] == spec.MAX_EFFECTIVE_BALANCE
    # first receipt pins the start index
    assert state.deposit_receipts_start_index == 7


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_top_up_existing_validator(spec, state):
    pre_count = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE // 4
    receipt = _receipt(spec, 0, amount, index=3)
    pre_balance = state.balances[0]
    spec.process_deposit_receipt(state, receipt)
    assert len(state.validators) == pre_count
    assert state.balances[0] == pre_balance + amount


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_invalid_signature_receipt_skipped(spec, state):
    """An invalid proof of possession skips the validator, like the
    legacy deposit path."""
    pre_count = len(state.validators)
    receipt = _receipt(spec, pre_count, spec.MAX_EFFECTIVE_BALANCE,
                       signed=False)
    spec.process_deposit_receipt(state, receipt)
    assert len(state.validators) == pre_count


@with_phases(["eip6110"])
@spec_state_test
def test_legacy_deposit_channel_winds_down(spec, state):
    """Once the receipts flow started and legacy deposits are consumed,
    blocks must carry zero legacy deposits (beacon-chain.md:194)."""
    state.deposit_receipts_start_index = 0
    state.eth1_deposit_index = state.eth1_data.deposit_count
    body = spec.BeaconBlockBody()
    # empty deposits list is required and accepted
    spec.process_operations(state, body)

    state2 = state.copy()
    state2.eth1_data.deposit_count += 1  # pretend an unprocessed deposit
    state2.eth1_deposit_index = 0
    state2.deposit_receipts_start_index = 0
    # limit = min(count, start=0) = 0 -> must carry zero deposits; a body
    # with any deposits is invalid, and the empty body passes
    spec.process_operations(state2, spec.BeaconBlockBody())


@with_phases(["eip6110"])
@spec_state_test
def test_receipts_processed_in_payload_order(spec, state):
    """process_operations consumes every payload receipt in order: a new
    validator followed by an immediate top-up of the same key."""
    pre_count = len(state.validators)
    new_index = pre_count
    amount = spec.MAX_EFFECTIVE_BALANCE
    top_up = spec.EFFECTIVE_BALANCE_INCREMENT
    body = spec.BeaconBlockBody()
    body.execution_payload.deposit_receipts = type(
        body.execution_payload.deposit_receipts)(
        _receipt(spec, new_index, amount, index=11),
        _receipt(spec, new_index, top_up, index=12),
    )
    spec.process_operations(state, body)
    assert len(state.validators) == pre_count + 1
    assert state.balances[new_index] == amount + top_up
    # the FIRST receipt pinned the start index; the second left it alone
    assert state.deposit_receipts_start_index == 11


@with_phases(["eip6110"])
@spec_state_test
def test_receipt_effective_balance_capped(spec, state):
    """A deposit above MAX_EFFECTIVE_BALANCE credits the full amount but
    caps the validator's effective balance (apply_deposit ->
    add_validator_to_registry semantics)."""
    new_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE * 2
    spec.process_deposit_receipt(
        state, _receipt(spec, new_index, amount, index=0))
    assert state.balances[new_index] == amount
    assert state.validators[new_index].effective_balance == \
        spec.MAX_EFFECTIVE_BALANCE


@with_phases(["eip6110"])
@spec_state_test
def test_top_up_leaves_effective_balance_until_epoch(spec, state):
    """A top-up raises the balance immediately; the effective balance
    only moves at the epoch-processing hysteresis update."""
    pre_effective = state.validators[0].effective_balance
    spec.process_deposit_receipt(
        state, _receipt(spec, 0, spec.EFFECTIVE_BALANCE_INCREMENT, index=2))
    assert state.validators[0].effective_balance == pre_effective


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_new_deposit_under_max(spec, state):
    new_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE - spec.EFFECTIVE_BALANCE_INCREMENT
    receipt = _receipt(spec, new_index, amount, index=0)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert state.balances[new_index] == amount
    assert state.validators[new_index].effective_balance == amount


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_new_deposit_over_max(spec, state):
    """Balance above the cap credits fully; effective balance caps."""
    new_index = len(state.validators)
    amount = spec.MAX_EFFECTIVE_BALANCE + spec.EFFECTIVE_BALANCE_INCREMENT
    receipt = _receipt(spec, new_index, amount, index=0)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert state.balances[new_index] == amount
    assert state.validators[new_index].effective_balance == \
        spec.MAX_EFFECTIVE_BALANCE


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_new_deposit_eth1_withdrawal_credentials(spec, state):
    """0x01 credentials are accepted as-is (no proof-of-possession tie)."""
    new_index = len(state.validators)
    pubkey = pubkeys[new_index]
    creds = spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\x42" * 20
    data = build_deposit_data(spec, pubkey, privkeys[new_index],
                              spec.MAX_EFFECTIVE_BALANCE, creds, signed=True)
    receipt = spec.DepositReceipt(
        pubkey=data.pubkey, withdrawal_credentials=creds,
        amount=data.amount, signature=data.signature, index=0)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert bytes(state.validators[new_index].withdrawal_credentials) == creds


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_invalid_sig_top_up_still_credits(spec, state):
    """Top-ups skip signature verification: a bad signature on an
    EXISTING validator's receipt still credits the balance."""
    pre_balance = state.balances[0]
    amount = spec.MIN_DEPOSIT_AMOUNT
    receipt = _receipt(spec, 0, amount, index=1, signed=False)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert state.balances[0] == pre_balance + amount


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_incorrect_withdrawal_credentials_top_up(spec, state):
    """Top-up with mismatched credentials still credits (credentials are
    only fixed at validator creation)."""
    pre_creds = bytes(state.validators[0].withdrawal_credentials)
    pubkey = pubkeys[0]
    wrong = spec.BLS_WITHDRAWAL_PREFIX + hash(b"other")[1:]
    data = build_deposit_data(spec, pubkey, privkeys[0],
                              spec.MIN_DEPOSIT_AMOUNT, wrong, signed=True)
    receipt = spec.DepositReceipt(
        pubkey=data.pubkey, withdrawal_credentials=wrong,
        amount=data.amount, signature=data.signature, index=2)
    pre_balance = state.balances[0]
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert state.balances[0] == pre_balance + spec.MIN_DEPOSIT_AMOUNT
    assert bytes(state.validators[0].withdrawal_credentials) == pre_creds


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_invalid_subgroup_pubkey_receipt_skipped(spec, state):
    """A pubkey failing KeyValidate never creates a validator."""
    from consensus_specs_tpu.ops.bls12_381.curve import G1Point
    from consensus_specs_tpu.ops.bls12_381.fields import Fq
    # an on-curve, non-subgroup point (cofactor component)
    for xi in range(1, 2000):
        x = Fq(xi)
        y = (x * x * x + Fq(4)).sqrt()
        if y is not None and not G1Point(x, y).in_subgroup():
            bad_pubkey = G1Point(x, y).to_compressed()
            break
    else:
        raise AssertionError("no non-subgroup point found")
    pre_count = len(state.validators)
    receipt = spec.DepositReceipt(
        pubkey=bad_pubkey,
        withdrawal_credentials=spec.BLS_WITHDRAWAL_PREFIX + b"\x00" * 31,
        amount=spec.MAX_EFFECTIVE_BALANCE,
        signature=b"\x11" * 96,
        index=0)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert len(state.validators) == pre_count


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_wrong_fork_version_sig_skipped(spec, state):
    """Deposit signatures bind the GENESIS fork domain
    (compute_domain with no fork version); a deposit message properly
    signed under the CURRENT fork\'s domain must fail verification and
    the receipt is skipped for new keys."""
    from consensus_specs_tpu.utils import bls as _bls
    new_index = len(state.validators)
    pubkey = pubkeys[new_index]
    creds = spec.BLS_WITHDRAWAL_PREFIX + hash(pubkey)[1:]
    data = build_deposit_data(spec, pubkey, privkeys[new_index],
                              spec.MAX_EFFECTIVE_BALANCE, creds,
                              signed=False)
    deposit_message = spec.DepositMessage(
        pubkey=data.pubkey, withdrawal_credentials=creds,
        amount=data.amount)
    # sign under the CURRENT fork version instead of the genesis domain
    wrong_domain = spec.compute_domain(
        spec.DOMAIN_DEPOSIT, state.fork.current_version,
        state.genesis_validators_root)
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    signing_root = spec.compute_signing_root_with_domain(
        deposit_message, wrong_domain) \
        if hasattr(spec, "compute_signing_root_with_domain") else \
        hash_tree_root(spec.SigningData(
            object_root=hash_tree_root(deposit_message),
            domain=wrong_domain))
    data.signature = _bls.Sign(privkeys[new_index], signing_root)
    receipt = spec.DepositReceipt(
        pubkey=data.pubkey, withdrawal_credentials=creds,
        amount=data.amount, signature=data.signature, index=0)
    pre_count = len(state.validators)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert len(state.validators) == pre_count


@with_phases(["eip6110"])
@spec_state_test
@always_bls
def test_top_up_withdrawn_validator(spec, state):
    """A receipt for an exited+withdrawable validator still credits."""
    current_epoch = spec.get_current_epoch(state)
    state.validators[0].exit_epoch = current_epoch
    state.validators[0].withdrawable_epoch = current_epoch
    pre_balance = state.balances[0]
    receipt = _receipt(spec, 0, spec.MIN_DEPOSIT_AMOUNT, index=5)
    yield "pre", state
    spec.process_deposit_receipt(state, receipt)
    yield "post", state
    assert state.balances[0] == pre_balance + spec.MIN_DEPOSIT_AMOUNT
