"""BLS ciphersuite edge cases (the bls vector family's adversarial set).

Reference model: ``tests/generators/bls/main.py`` edge cases — infinity
points, empty aggregations, tampered/non-canonical encodings — against
the IETF BLS spec semantics the reference inherits from py_ecc/milagro.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.utils import bls

Z1_PUBKEY = b"\xc0" + b"\x00" * 47
Z2_SIGNATURE = b"\xc0" + b"\x00" * 95
MSG = b"\xab" * 32


def setup_module():
    bls.use_py()
    bls.bls_active = True


def test_keyvalidate_rejects_infinity_pubkey():
    assert not bls.KeyValidate(Z1_PUBKEY)


def test_keyvalidate_rejects_garbage():
    assert not bls.KeyValidate(b"\x12" * 48)
    # valid compressed flag but off-curve x
    assert not bls.KeyValidate(b"\xa0" + b"\x00" * 47)


def test_keyvalidate_accepts_real_pubkey():
    assert bls.KeyValidate(bls.SkToPk(42))


def test_verify_rejects_infinity_pubkey():
    sig = bls.Sign(1, MSG)
    assert not bls.Verify(Z1_PUBKEY, MSG, sig)


def test_verify_rejects_infinity_signature():
    pk = bls.SkToPk(1)
    assert not bls.Verify(pk, MSG, Z2_SIGNATURE)


def test_fast_aggregate_verify_empty_pubkeys_false():
    """IETF: FastAggregateVerify over zero pubkeys is invalid — even with
    the infinity signature (the altair eth_ variant special-cases it)."""
    assert not bls.FastAggregateVerify([], MSG, Z2_SIGNATURE)


def test_aggregate_empty_signature_list_raises():
    with pytest.raises(Exception):
        bls.Aggregate([])


def test_aggregate_verify_mismatched_lengths_false():
    pks = [bls.SkToPk(1), bls.SkToPk(2)]
    sig = bls.Aggregate([bls.Sign(1, MSG)])
    assert not bls.AggregateVerify(pks, [MSG], sig)


def test_sign_verify_distinct_messages_aggregate():
    pairs = [(1, b"\x01" * 32), (2, b"\x02" * 32), (3, b"\x03" * 32)]
    sig = bls.Aggregate([bls.Sign(sk, m) for sk, m in pairs])
    pks = [bls.SkToPk(sk) for sk, _ in pairs]
    msgs = [m for _, m in pairs]
    assert bls.AggregateVerify(pks, msgs, sig)
    # reordering messages breaks it
    assert not bls.AggregateVerify(pks, msgs[::-1], sig)


def test_signature_malleability_rejected():
    """Flipping the compression sign bit must not verify."""
    sig = bytearray(bls.Sign(7, MSG))
    sig[0] ^= 0x20  # flip the sort flag
    assert not bls.Verify(bls.SkToPk(7), MSG, bytes(sig))


def test_noncanonical_signature_rejected():
    """x >= p in the encoding is non-canonical."""
    assert not bls.Verify(bls.SkToPk(7), MSG, b"\xbf" + b"\xff" * 95)


def test_stub_mode_behaviour():
    old = bls.bls_active
    bls.bls_active = False
    try:
        assert bls.Sign(1, MSG) == bls.STUB_SIGNATURE
        assert bls.SkToPk(1) == bls.STUB_PUBKEY
        assert bls.Verify(b"\x00" * 48, MSG, b"\x00" * 96)
    finally:
        bls.bls_active = old
