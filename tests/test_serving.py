"""Block-serving pipeline suite (``consensus_specs_tpu/serving``):
pipelined-vs-synchronous byte-identity on captured adversarial load
streams, the fault-injection / flush-failure / corrupt-audit / deadline
fallback legs for the ``serving.pipeline`` site, the one-pairing-per-
window census, chunk-level clone semantics (laziness, the frozen-source
contract, fast-lineage propagation), and the concurrent-head stress
differential (N divergent chunk-level clones vs independent full-copy
replays)."""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu import faults, supervisor
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.serving import BlockServer, clone_state
from consensus_specs_tpu.serving import pipeline
from consensus_specs_tpu.sim import load
from consensus_specs_tpu.test_infra.genesis import create_genesis_state
from consensus_specs_tpu.test_infra.metrics import counting
from consensus_specs_tpu.utils import bls
from consensus_specs_tpu.utils.ssz import hash_tree_root

SITE = "serving.pipeline"

_streams = {}       # scenario name -> LoadStream (built once per session)
_sync_refs = {}     # scenario name -> (digest, results) synchronous oracle


@pytest.fixture(scope="module")
def spec():
    return build_spec("phase0", "minimal")


@pytest.fixture(autouse=True)
def _serving_on(monkeypatch):
    """Pin the engine switch ON regardless of the process env (the CI
    off-leg runs the whole suite under CS_TPU_SERVING=0; off-behavior
    tests override to \"0\" themselves — the switch reads live)."""
    monkeypatch.setenv("CS_TPU_SERVING", "1")


def _stream(spec, name):
    s = _streams.get(name)
    if s is None:
        s = _streams[name] = load.generate(spec, seed=3, name=name)
    return s


def _sync_ref(spec, name):
    """The synchronous oracle for one stream: digest + per-block
    verdicts of a serving-OFF replay, computed once."""
    ref = _sync_refs.get(name)
    if ref is None:
        prev = os.environ.get("CS_TPU_SERVING")
        os.environ["CS_TPU_SERVING"] = "0"
        try:
            store = load.anchor_store(spec, _stream(spec, name))
            results = load.serve(BlockServer(spec, store),
                                 _stream(spec, name))
        finally:
            if prev is None:
                os.environ.pop("CS_TPU_SERVING", None)
            else:
                os.environ["CS_TPU_SERVING"] = prev
        ref = _sync_refs[name] = (load.store_digest(spec, store), results)
    return ref


def _serve_pipelined(spec, name, window=3):
    stream = _stream(spec, name)
    store = load.anchor_store(spec, stream)
    results = load.serve(BlockServer(spec, store, window=window), stream)
    return load.store_digest(spec, store), results


# ---------------------------------------------------------------------------
# lane differential + engine citizenship legs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", load.DEFAULT_MIX)
def test_pipelined_lane_byte_identical_to_sync(spec, name):
    """Window batching + overlapped flush + chunk-level snapshots must
    not move a single byte of consensus state: deep store digests and
    per-block accept/reject verdicts match the synchronous oracle."""
    ref_digest, ref_results = _sync_ref(spec, name)
    with counting() as delta:
        digest, results = _serve_pipelined(spec, name)
    assert digest == ref_digest
    assert results == ref_results
    n_blocks = _stream(spec, name).n_blocks
    assert delta["serving.blocks{path=pipelined}"] == n_blocks
    assert delta["serving.blocks{path=sync}"] == 0
    assert delta["serving.windows"] > 0
    assert delta["serving.clones"] > 0
    assert sum(v for k, v in delta.items()
               if k.startswith("serving.fallbacks")) == 0


def test_serving_off_leg_counts_sync_path(spec, monkeypatch):
    monkeypatch.setenv("CS_TPU_SERVING", "0")
    ref_digest, ref_results = _sync_ref(spec, "equivocation")
    with counting() as delta:
        digest, results = _serve_pipelined(spec, "equivocation")
    assert digest == ref_digest and results == ref_results
    assert delta["serving.blocks{path=sync}"] == \
        _stream(spec, "equivocation").n_blocks
    assert delta["serving.blocks{path=pipelined}"] == 0
    assert delta["serving.windows"] == 0


def test_injected_fault_falls_back_counted(spec):
    """An injected fault at the first window rolls back and replays it
    synchronously — byte-identical result, exactly one counted
    ``reason=injected`` trip, later windows still pipelined."""
    ref_digest, ref_results = _sync_ref(spec, "equivocation")
    sched = faults.FaultSchedule({SITE: [1]})
    with counting() as delta:
        with faults.injected(sched):
            digest, results = _serve_pipelined(spec, "equivocation")
    assert digest == ref_digest and results == ref_results
    assert sched.fully_fired(), (sched.planned, sched.fired)
    assert delta["serving.fallbacks{reason=injected}"] == 1
    assert delta["serving.blocks{path=sync}"] > 0
    assert delta["serving.blocks{path=pipelined}"] > 0


def test_flush_failure_reverifies_synchronously(spec, monkeypatch):
    """A worker-lane flush verdict of False (forced here; organically a
    bad signature) unwinds BOTH in-flight windows at the barrier and
    reverifies per-block — byte-identical, counted ``reason=reverify``,
    zero blocks left on the pipelined series."""
    ref_digest, ref_results = _sync_ref(spec, "equivocation")
    monkeypatch.setattr(pipeline._WindowBatch, "resolve",
                        lambda self: False)
    with counting() as delta:
        digest, results = _serve_pipelined(spec, "equivocation")
    assert digest == ref_digest and results == ref_results
    assert delta["serving.fallbacks{reason=reverify}"] > 0
    assert delta["serving.blocks{path=pipelined}"] == 0
    assert delta["serving.blocks{path=sync}"] == \
        _stream(spec, "equivocation").n_blocks


def test_corrupt_audit_catches_tamper_and_quarantines(
        spec, monkeypatch, tmp_path):
    """Corrupt-mode injection tampers a pipelined post-state; the
    rate-1 sentinel audit at the window barrier must catch the
    divergence, quarantine the site, and serve the rest of the stream
    synchronously — post-drain store still byte-identical."""
    monkeypatch.setenv("CS_TPU_SUPERVISOR", "1")
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("CS_TPU_BREAKER_THRESHOLD", "1000000000")
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))
    supervisor.reset()
    try:
        ref_digest, ref_results = _sync_ref(spec, "equivocation")
        sched = faults.FaultSchedule(corrupt={SITE: [1]})
        with counting() as delta:
            with faults.injected(sched):
                digest, results = _serve_pipelined(spec, "equivocation")
        assert digest == ref_digest and results == ref_results
        assert sched.corrupted, "corrupt injection never armed"
        assert delta[
            "supervisor.audits{result=fail,site=serving.pipeline}"] == 1
        assert delta[
            "supervisor.quarantines{site=serving.pipeline}"] == 1
        assert supervisor.states()[SITE] == "quarantined"
        assert delta["serving.fallbacks{reason=reverify}"] == 1
    finally:
        supervisor.reset()


def test_deadline_falls_back_counted(spec, monkeypatch):
    """A spent per-window deadline budget converts the optimistic pass
    into a counted ``reason=deadline`` synchronous replay."""
    monkeypatch.setenv("CS_TPU_SUPERVISOR", "1")
    monkeypatch.setenv("CS_TPU_DEADLINE_MS", "0.0001")
    monkeypatch.setenv("CS_TPU_BREAKER_THRESHOLD", "1000000000")
    supervisor.reset()
    try:
        ref_digest, ref_results = _sync_ref(spec, "equivocation")
        with counting() as delta:
            digest, results = _serve_pipelined(spec, "equivocation")
        assert digest == ref_digest and results == ref_results
        assert delta["serving.fallbacks{reason=deadline}"] > 0
        assert delta["serving.blocks{path=pipelined}"] == 0
        assert delta["serving.blocks{path=sync}"] == \
            _stream(spec, "equivocation").n_blocks
    finally:
        supervisor.reset()


def test_one_pairing_per_window_census(spec):
    """With real signatures, the window's combined flush must fold to
    EXACTLY one pairing per window — strictly below the sync lane's
    one-per-block count — without moving a byte."""
    if not bls.bls_active:
        pytest.skip("needs --enable-bls (real pairings)")
    name = "equivocation"
    ref_digest, _ = _sync_ref(spec, name)
    bls.clear_verify_memo()
    with counting() as sync_delta:
        os.environ["CS_TPU_SERVING"] = "0"
        try:
            store = load.anchor_store(spec, _stream(spec, name))
            load.serve(BlockServer(spec, store), _stream(spec, name))
        finally:
            os.environ["CS_TPU_SERVING"] = "1"
    bls.clear_verify_memo()
    with counting() as pipe_delta:
        digest, _ = _serve_pipelined(spec, name, window=4)
    assert digest == ref_digest
    windows = pipe_delta["serving.windows"]
    assert windows > 0
    assert pipe_delta["bls.pairings"] == windows, \
        (pipe_delta["bls.pairings"], windows)
    assert sync_delta["bls.pairings"] > pipe_delta["bls.pairings"]


# ---------------------------------------------------------------------------
# chunk-level clones
# ---------------------------------------------------------------------------

def _genesis(spec, n=64):
    return create_genesis_state(
        spec, [spec.MAX_EFFECTIVE_BALANCE] * n, spec.MAX_EFFECTIVE_BALANCE)


def test_clone_state_byte_identity_and_isolation(spec):
    state = _genesis(spec)
    base_root = bytes(hash_tree_root(state))
    with counting() as delta:
        cl = clone_state(state)
    assert delta["serving.clones"] == 1
    assert delta["serving.clone_fields{mode=fast}"] > 0
    assert delta["serving.clone_fields{mode=lazy}"] > 0
    assert bytes(hash_tree_root(cl)) == base_root
    # divergent mutation of a fast field and a lazy field: the clone
    # tracks a full copy mutated identically, the source never moves
    ref = state.copy()
    for st in (ref, cl):
        st.balances[1] = st.balances[1] + 7
        st.validators[0].effective_balance = \
            st.validators[0].effective_balance + 1
    mutated = bytes(hash_tree_root(ref))
    assert bytes(hash_tree_root(cl)) == mutated
    assert mutated != base_root
    assert bytes(hash_tree_root(state)) == base_root


def test_lazy_clone_defers_until_touched(spec):
    state = _genesis(spec)
    with counting() as delta:
        cl = clone_state(state)
    assert delta["serving.materializations{stage=items}"] == 0, \
        "clone_state paid the per-element walk up front"
    with counting() as delta:
        cl.validators[0]                      # first touch materializes
    assert delta["serving.materializations{stage=items}"] == 1
    with counting() as delta:
        cl.validators[1]
    assert delta["serving.materializations{stage=items}"] == 0


def test_lazy_clone_frozen_source_contract(spec):
    """Mutating the source after a chunk-level clone must fail the
    clone's deferred touches loudly — never materialize drifted data."""
    state = _genesis(spec)
    cl = clone_state(state)
    state.validators[0].effective_balance = \
        state.validators[0].effective_balance + 1
    with pytest.raises(RuntimeError, match="frozen"):
        cl.validators[0]
    # a clone taken from the new (post-mutation) source state is fine
    assert bytes(hash_tree_root(clone_state(state))) == \
        bytes(hash_tree_root(state))


def test_fast_clone_lineage_stays_fast(spec):
    """``copy()`` of a cloned state's immutable-element sequences must
    stay on the C-level fast path through the whole lineage (fork
    choice copies snapshots of snapshots)."""
    state = _genesis(spec)
    cl = clone_state(state)
    assert getattr(type(cl.balances), "_serving_fast", False)
    with counting() as delta:
        again = cl.balances.copy()
    assert delta["serving.clone_fields{mode=fast}"] == 1
    assert getattr(type(again), "_serving_fast", False)
    assert type(again) is type(cl.balances)    # no subclass nesting
    assert list(again) == list(cl.balances)


# ---------------------------------------------------------------------------
# concurrent-head stress: N divergent clones vs independent replays
# ---------------------------------------------------------------------------

def _concurrent_heads(spec, n_validators, replays):
    slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
    state = _genesis(spec, n_validators)
    spec.process_slots(state, slots_per_epoch)
    base_root = bytes(hash_tree_root(state))
    half = int(spec.MAX_EFFECTIVE_BALANCE) // 2

    with counting() as delta:
        clones = [clone_state(state) for _ in range(replays)]
    assert delta["serving.clones"] == replays
    assert delta["serving.materializations{stage=items}"] == 0

    cloned_roots = []
    for k, st in enumerate(clones):
        st.balances[k % n_validators] = half + k
        spec.process_slots(st, int(st.slot) + slots_per_epoch)
        cloned_roots.append(bytes(hash_tree_root(st)))

    independent_roots = []
    for k in range(replays):
        st = state.copy()
        st.balances[k % n_validators] = half + k
        spec.process_slots(st, int(st.slot) + slots_per_epoch)
        independent_roots.append(bytes(hash_tree_root(st)))

    assert cloned_roots == independent_roots, \
        "a divergently-advanced chunk-level clone diverged from its " \
        "independent full-copy replay"
    assert len(set(cloned_roots)) == replays   # heads really diverged
    assert bytes(hash_tree_root(state)) == base_root, \
        "advancing clones disturbed the shared base snapshot"


def test_concurrent_heads_divergent_clones(spec):
    _concurrent_heads(spec, n_validators=256, replays=4)


@pytest.mark.slow
def test_concurrent_heads_divergent_clones_1m():
    """The ISSUE-scale leg: divergent heads off one 1M-column state."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks"))
    from bench_state_arrays import build_state
    spec = build_spec("altair", "minimal")
    slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
    state = build_state(spec, 1 << 20)
    spec.process_slots(state, slots_per_epoch)
    base_root = bytes(hash_tree_root(state))
    clones = [clone_state(state) for _ in range(4)]
    roots = []
    for k, st in enumerate(clones):
        st.balances[k] = st.balances[k] - (k + 1)
        spec.process_slots(st, int(st.slot) + slots_per_epoch)
        roots.append(bytes(hash_tree_root(st)))
    for k in range(4):
        st = state.copy()
        st.balances[k] = st.balances[k] - (k + 1)
        spec.process_slots(st, int(st.slot) + slots_per_epoch)
        assert bytes(hash_tree_root(st)) == roots[k]
    assert bytes(hash_tree_root(state)) == base_root


# ---------------------------------------------------------------------------
# causal tracing + flight recorder over the pipeline
# ---------------------------------------------------------------------------

def _walk_assert_no_orphans(name, node):
    assert "orphan" not in node, f"orphan-flagged span: {name}"
    for child_name, child in node.get("children", {}).items():
        _walk_assert_no_orphans(child_name, child)


@pytest.fixture()
def _traced():
    from consensus_specs_tpu.obs import tracing
    tracing.enable(True, counters=False)
    tracing.reset()
    yield tracing
    tracing.enable(False)
    tracing.reset()


def test_pipelined_replay_one_causal_tree_per_window(spec, _traced):
    """Acceptance: under tracing, a pipelined replay yields ONE
    causally-linked tree per window — the worker-lane flush and the
    barrier join are CHILDREN of their window's span, never disjoint
    roots, and nothing is orphan-flagged."""
    stream = _stream(spec, "equivocation")       # built before tracing
    _traced.reset()
    store = load.anchor_store(spec, stream)
    server = BlockServer(spec, store, window=3)
    load.serve(server, stream)
    tree = _traced.span_tree()
    win = tree["serving.window"]
    n_windows = win["count"]
    assert n_windows > 0
    assert win["children"]["serving.flush"]["count"] == n_windows
    assert win["children"]["serving.barrier"]["count"] == n_windows
    # no disjoint roots for the cross-thread legs
    assert "serving.flush" not in tree
    assert "serving.barrier" not in tree
    for name, node in tree.items():
        _walk_assert_no_orphans(name, node)
    # the per-window latency log carries one entry per window with
    # distinct trace ids and the span-aligned stats
    log = server.window_log
    assert len(log) == n_windows
    ids = [e["trace_id"] for e in log]
    assert len(set(ids)) == len(ids) and None not in ids
    for entry in log:
        assert entry["outcome"] == "pipelined"
        for key in ("queued_s", "optimistic_s", "flush_s", "barrier_s"):
            assert entry[key] >= 0.0


def test_replayed_window_keeps_causal_tree(spec, _traced, monkeypatch):
    """A window whose worker-lane flush fails replays synchronously at
    the barrier — still inside the window's trace (span
    ``serving.replay``), logged with ``outcome=replayed``."""
    stream = _stream(spec, "equivocation")
    _traced.reset()
    monkeypatch.setattr(pipeline._WindowBatch, "resolve",
                        lambda self: False)
    store = load.anchor_store(spec, stream)
    server = BlockServer(spec, store, window=3)
    load.serve(server, stream)
    tree = _traced.span_tree()
    win = tree["serving.window"]
    assert win["children"]["serving.replay"]["count"] >= 1
    assert "serving.replay" not in tree
    replayed = [e for e in server.window_log
                if e["outcome"] == "replayed"]
    assert len(replayed) >= 1
    assert all(e["replay_s"] >= 0.0 for e in replayed)


def test_untraced_replay_logs_windows_without_ids(spec):
    """Tracing off: the latency log still accumulates (stats cost a
    few clocks), trace ids are None — no context machinery engaged."""
    stream = _stream(spec, "equivocation")
    store = load.anchor_store(spec, stream)
    server = BlockServer(spec, store, window=3)
    load.serve(server, stream)
    assert server.window_log
    assert all(e["trace_id"] is None for e in server.window_log)


def test_lost_context_windows_flagged_as_orphans(spec, _traced,
                                                monkeypatch):
    """Satellite regression: if window submission loses its captured
    context (capture_context returning None), the worker-lane spans
    must surface as FLAGGED orphan roots in the tree and the rendered
    report — never silently merge into an unrelated tree."""
    from consensus_specs_tpu.obs import export, tracing
    monkeypatch.setattr(tracing, "capture_context", lambda: None)
    stream = _stream(spec, "equivocation")
    _traced.reset()
    store = load.anchor_store(spec, stream)
    server = BlockServer(spec, store, window=3)
    load.serve(server, stream)
    tree = _traced.span_tree()
    assert tree["serving.flush"]["orphan"] is True
    assert "serving.flush" not in tree["serving.window"]["children"]
    assert "[orphan thread]" in export.report()
    assert all(e["trace_id"] is None for e in server.window_log)


def test_quarantine_artifact_carries_flight_dump(spec, monkeypatch,
                                                 tmp_path):
    """Acceptance: a forced quarantine's artifact embeds a non-empty
    flight dump (the last-N-events tail, flush-worker lane included)
    in the format ``sim.repro`` prints before replaying."""
    from consensus_specs_tpu.obs import flight
    monkeypatch.setenv("CS_TPU_SUPERVISOR", "1")
    monkeypatch.setenv("CS_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("CS_TPU_BREAKER_THRESHOLD", "1000000000")
    monkeypatch.setenv("CS_TPU_SIM_ARTIFACTS", str(tmp_path))
    supervisor.reset()
    flight.reset(refresh_env=True)
    flight.enable(True)
    try:
        sched = faults.FaultSchedule(corrupt={SITE: [1]})
        with faults.injected(sched):
            _serve_pipelined(spec, "equivocation")
        assert supervisor.states()[SITE] == "quarantined"
        path = supervisor.last_quarantine()
        assert path and os.path.exists(path)
        with open(path) as f:
            payload = json.load(f)
        dump = payload["flight"]
        assert dump["trigger"] == "quarantine"
        assert dump["threads"], "quarantine artifact flight dump empty"
        assert any(recs for recs in dump["threads"].values())
        # the windows the pipeline submitted are in the tail
        codes = [r[2] for recs in dump["threads"].values()
                 for r in recs]
        assert "window" in codes and "breaker" in codes
        text = flight.format_dump(dump)
        assert "quarantine" in text
    finally:
        supervisor.reset()
        flight.reset(refresh_env=True)


def test_flight_dump_deterministic_across_seeded_replays(spec):
    """Two identical seeded replays leave identical flight tails
    (codes + details per thread role; sequence numbers and wall-clock
    stripped) — the dump is replay evidence, not noise."""
    from consensus_specs_tpu.obs import flight, tracing

    def one_tail():
        flight.reset()
        flight.enable(True)
        tracing.enable(True, counters=False)
        tracing.reset()
        try:
            _serve_pipelined(spec, "equivocation")
            d = flight.dump(trigger="manual")
            # normalize: thread NAMES differ per run (thread counter),
            # so key by role = records observed on main vs worker
            return {
                "main": [(r[2], r[3]) for r in
                         d["threads"].get("MainThread", [])],
                "workers": sorted(
                    tuple((r[2], r[3]) for r in recs)
                    for name, recs in d["threads"].items()
                    if name != "MainThread"),
            }
        finally:
            tracing.enable(False)
            tracing.reset()
            flight.enable(False)

    first, second = one_tail(), one_tail()
    assert first["main"] and first["workers"]
    assert first == second
    flight.reset(refresh_env=True)
