"""speclint fixture suite.

Each domain pass must (a) flag its planted-bug fixture and (b) stay
quiet on the safe idiom right next to it; the driver must run clean on
the real tree modulo the checked-in baseline, and the ratchet must fail
when debt grows.  The synthetic ladder-drift test copies the REAL fork
ladder and removes one function from a compiled module — the exact
regression the pass exists for.
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from consensus_specs_tpu.tools.speclint import driver
from consensus_specs_tpu.tools.speclint.findings import (
    Finding, noqa_codes, suppressed)
from consensus_specs_tpu.tools.speclint.passes import (
    fallbacks, ladder, obs as obs_pass, specmd, state_layer, style,
    supervision, tracing, uint64)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCOPED = "consensus_specs_tpu/ops/epoch_kernels.py"   # in uint64 pass scope


@pytest.fixture(scope="module", autouse=True)
def _ensure_compiled_ladder():
    """forks/compiled/ is generated (gitignored): on a fresh checkout
    build it once so the real-tree ladder tests compare real surfaces
    (CI's lint job runs `make pyspec` for the same reason)."""
    if not os.path.isdir(os.path.join(REPO, "consensus_specs_tpu",
                                      "forks", "compiled")):
        subprocess.run([sys.executable, "-m", "consensus_specs_tpu.compiler"],
                       check=True, cwd=REPO, capture_output=True)


def _codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# uint64-hazard pass
# ---------------------------------------------------------------------------

def test_uint64_flags_unsigned_subtraction():
    src = (
        "import numpy as np\n"
        "def f(seq):\n"
        "    balances = u64_column(seq)\n"
        "    penalties = u64_column(seq)\n"
        "    return balances - penalties\n")
    assert "U101" in _codes(uint64.check_source(SCOPED, src))


def test_uint64_accepts_clamp_idioms():
    src = (
        "import numpy as np\n"
        "def kernel(xp, balances, rewards, penalties):\n"
        "    up = balances + rewards\n"
        "    safe1 = xp.where(penalties > up, xp.uint64(0), up - penalties)\n"
        "    safe2 = up - xp.minimum(penalties, up)\n"
        "    safe3 = up - up % xp.uint64(32)\n"
        "    return safe1, safe2, safe3\n")
    assert [c for c in _codes(uint64.check_source(SCOPED, src))
            if c == "U101"] == []


def test_uint64_flags_unguarded_multiplication():
    src = (
        "def f(seq, factor):\n"
        "    eff = u64_column(seq)\n"
        "    return eff * factor\n")
    assert "U102" in _codes(uint64.check_source(SCOPED, src))


def test_uint64_mult_discharged_by_guard_or_pragma():
    guarded = (
        "def f(seq, factor):\n"
        "    eff = u64_column(seq)\n"
        "    _guard(int(eff.max(initial=0)) * factor)\n"
        "    return eff * factor\n")
    assert "U102" not in _codes(uint64.check_source(SCOPED, guarded))
    pragma = (
        "# speclint: guarded-by-caller (bounds checked in try_process_*)\n"
        "def kernel(xp, eff, factor):\n"
        "    return eff * factor\n")
    assert "U102" not in _codes(uint64.check_source(SCOPED, pragma))


def test_uint64_flags_dtypeless_reduction():
    src = (
        "def f(seq):\n"
        "    mask = u64_column(seq)\n"
        "    n_bad = int(mask.sum())\n"
        "    n_ok = int(mask.sum(dtype='int64'))\n"
        "    return n_bad, n_ok\n")
    assert _codes(uint64.check_source(SCOPED, src)).count("U103") == 1


def test_uint64_flags_augmented_assignment():
    """`b -= p` / `b *= p` are the in-place spelling of the hazard and
    must behave exactly like `b = b - p`, clamp idioms included."""
    src = (
        "def f(seq):\n"
        "    b = u64_column(seq)\n"
        "    p = u64_column(seq)\n"
        "    b -= p\n"
        "    b *= p\n")
    codes = _codes(uint64.check_source(SCOPED, src))
    assert "U101" in codes and "U102" in codes
    clamped = (
        "def f(xp, seq):\n"
        "    b = u64_column(seq)\n"
        "    p = u64_column(seq)\n"
        "    b -= xp.minimum(p, b)\n")
    assert "U101" not in _codes(uint64.check_source(SCOPED, clamped))


def test_uint64_taint_flows_through_nested_blocks():
    """Assignments inside if/for bodies must update the taint set, and
    a _guard() inside a branch must discharge a later multiply.
    (Two INDEPENDENT columns: `b - b` itself is now proven safe by the
    range prover — x - x cannot wrap — and no longer fires.)"""
    src = (
        "def f(seq, flag):\n"
        "    if flag:\n"
        "        b = u64_column(seq)\n"
        "        p = u64_column(seq)\n"
        "        return b - p\n"
        "    return None\n")
    assert "U101" in _codes(uint64.check_source(SCOPED, src))
    guarded = (
        "def f(seq, flag, factor):\n"
        "    eff = u64_column(seq)\n"
        "    if flag:\n"
        "        _guard(int(eff.max(initial=0)) * factor)\n"
        "        return eff * factor\n"
        "    return eff\n")
    assert "U102" not in _codes(uint64.check_source(SCOPED, guarded))


def test_uint64_out_of_scope_files_ignored(tmp_path):
    bad = "def f(seq):\n    return u64_column(seq) - u64_column(seq)\n"
    root = tmp_path / "repo"
    target = root / SCOPED
    target.parent.mkdir(parents=True)
    target.write_text(bad)
    other = root / "consensus_specs_tpu" / "utils" / "misc.py"
    other.parent.mkdir(parents=True)
    other.write_text(bad)
    findings = uint64.run(driver.Context(str(root)))
    assert {f.path for f in findings} == {SCOPED}


# ---------------------------------------------------------------------------
# jax-tracing pass
# ---------------------------------------------------------------------------

def test_tracing_flags_concretization_in_jitted_fn():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return int(x) + x.item()\n")
    assert _codes(tracing.check_source("m.py", src)).count("J201") == 2


def test_tracing_untraced_function_not_flagged():
    src = (
        "def host_only(x):\n"
        "    return int(x) + time.time()\n")
    assert tracing.check_source("m.py", src) == []


def test_tracing_flags_impurity_and_loops_transitively():
    src = (
        "import jax, time\n"
        "def helper(x):\n"
        "    t = time.time()\n"
        "    while x > 0:\n"
        "        x = x - 1\n"
        "    return x + t\n"
        "def outer(x):\n"
        "    return helper(x)\n"
        "prog = jax.jit(outer)\n")
    codes = _codes(tracing.check_source("m.py", src))
    assert "J202" in codes and "J203" in codes


def test_tracing_static_unrolls_exempt():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    for i in range(8):\n"
        "        x = x + i\n"
        "    for w in (1, 2, 3):\n"
        "        x = x * w\n"
        "    return x\n")
    assert tracing.check_source("m.py", src) == []


def test_tracing_constant_baking_asarray_exempt():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    k = jnp.asarray(_K_TABLE)\n"
        "    return jnp.asarray(x) + k\n")
    assert _codes(tracing.check_source("m.py", src)).count("J201") == 1


# ---------------------------------------------------------------------------
# ladder-drift pass
# ---------------------------------------------------------------------------

def _mini_ladder(tmp_path, compiled_body, hand_body=None):
    root = tmp_path / "repo"
    forks = root / "consensus_specs_tpu" / "forks"
    compiled = forks / "compiled"
    compiled.mkdir(parents=True)
    (forks / "foo.py").write_text(hand_body or (
        "class FooSpec:\n"
        "    fork = 'foo'\n"
        "    def process_thing(self, state, index):\n"
        "        return state\n"
        "    def get_value(self, state):\n"
        "        return 1\n"))
    (compiled / "foo.py").write_text(compiled_body)
    return str(root)


_COMPILED_OK = (
    '"""AUTO-COMPILED from specs/foo.md — do not edit."""\n'
    "class CompiledFooSpec:\n"
    "    fork = 'foo'\n"
    "    def process_thing(self, state, index):\n"
    "        return state\n"
    "    def get_value(self, state):\n"
    "        return 1\n")


def test_ladder_clean_on_matching_pair(tmp_path):
    assert ladder.check_tree(_mini_ladder(tmp_path, _COMPILED_OK)) == []


def test_ladder_flags_missing_compiled_tree(tmp_path):
    """A hand ladder with no compiled counterpart tree (fresh checkout
    before `make pyspec`) must be an explicit finding, not a silent
    green no-op."""
    root = tmp_path / "repo"
    forks = root / "consensus_specs_tpu" / "forks"
    forks.mkdir(parents=True)
    (forks / "foo.py").write_text("class FooSpec:\n    fork = 'foo'\n")
    findings = ladder.check_tree(str(root))
    assert _codes(findings) == ["L300"]
    assert "make pyspec" in findings[0].message


def test_ladder_detects_missing_function(tmp_path):
    dropped = _COMPILED_OK.replace(
        "    def get_value(self, state):\n        return 1\n", "")
    findings = ladder.check_tree(_mini_ladder(tmp_path, dropped))
    assert ["L301"] == _codes(findings)
    assert "get_value" in findings[0].message


def test_ladder_detects_signature_drift(tmp_path):
    drifted = _COMPILED_OK.replace("def process_thing(self, state, index)",
                                   "def process_thing(self, state, idx)")
    findings = ladder.check_tree(_mini_ladder(tmp_path, drifted))
    assert ["L302"] == _codes(findings)


def test_ladder_detects_missing_header_and_hand_edit(tmp_path):
    hacked = _COMPILED_OK.replace(
        '"""AUTO-COMPILED from specs/foo.md — do not edit."""',
        "# HAND-EDIT: patched in place\n")
    findings = ladder.check_tree(_mini_ladder(tmp_path, hacked))
    assert sorted(_codes(findings)) == ["L303", "L304"]


def test_ladder_synthetic_drift_on_real_tree(tmp_path):
    """Acceptance fixture: remove one public function from a COPY of a
    real compiled module; the pass must catch the drift."""
    root = tmp_path / "repo"
    dst = root / "consensus_specs_tpu" / "forks"
    shutil.copytree(os.path.join(REPO, "consensus_specs_tpu", "forks"), dst,
                    ignore=shutil.ignore_patterns("__pycache__"))
    assert ladder.check_tree(str(root)) == []   # pristine copy is clean

    mod = dst / "compiled" / "altair.py"
    text = mod.read_text().split("\n")
    # drop the body of one public spec method (keep the file parseable)
    start = next(i for i, ln in enumerate(text)
                 if ln.strip().startswith("def get_flag_index_deltas"))
    indent = len(text[start]) - len(text[start].lstrip())
    end = start + 1
    while end < len(text) and (not text[end].strip()
                               or len(text[end]) - len(text[end].lstrip())
                               > indent):
        end += 1
    mod.write_text("\n".join(text[:start] + text[end:]))
    findings = ladder.check_tree(str(root))
    assert any(f.code == "L301" and "get_flag_index_deltas" in f.message
               for f in findings)


# ---------------------------------------------------------------------------
# spec-markdown pass
# ---------------------------------------------------------------------------

def test_specmd_flags_banned_constructs():
    md = (
        "# Demo spec\n"
        "\n"
        "```python\n"
        "import os\n"
        "def get_rate() -> uint64:\n"
        "    return uint64(0.5 * random.random())\n"
        "```\n")
    codes = _codes(specmd.check_markdown("specs/demo.md", md))
    assert codes.count("M401") == 1    # import
    assert codes.count("M402") == 1    # float literal
    assert codes.count("M403") == 1    # random.random()


def test_specmd_line_anchoring():
    md = "line1\n\n```python\nx = GOOD\nimport os\n```\n"
    (finding,) = specmd.check_markdown("specs/demo.md", md)
    assert (finding.code, finding.line) == ("M401", 5)


def test_specmd_unterminated_fence():
    md = "# Demo\n\n```python\nx = 1\n"
    (finding,) = specmd.check_markdown("specs/demo.md", md)
    assert (finding.code, finding.line) == ("M400", 3)


def test_specmd_unparsable_block():
    md = "```python\n    dangling indent\n```\n"
    (finding,) = specmd.check_markdown("specs/demo.md", md)
    assert finding.code == "M404"


def test_specmd_clean_block_passes():
    md = (
        "```python\n"
        "def get_current_epoch(state: BeaconState) -> Epoch:\n"
        "    return compute_epoch_at_slot(state.slot)\n"
        "```\n")
    assert specmd.check_markdown("specs/demo.md", md) == []


# ---------------------------------------------------------------------------
# observability pass
# ---------------------------------------------------------------------------

def test_obs_flags_bare_clock_on_hot_path():
    src = (
        "import time\n"
        "def hot(xs):\n"
        "    t0 = time.perf_counter()\n"
        "    work(xs)\n"
        "    return time.time() - t0\n")
    codes = _codes(obs_pass.check_source(SCOPED, src))
    assert codes == ["O501", "O501"]


def test_obs_flags_per_call_metric_resolution():
    src = (
        "from consensus_specs_tpu.obs import registry\n"
        "def hot(xs):\n"
        "    registry.counter('m.x').inc()\n"
        "    s = registry.counter('m.y').labels(backend='jax')\n"
        "    s.add(len(xs))\n")
    codes = _codes(obs_pass.check_source(SCOPED, src))
    # the chained counter().labels() line reports once
    assert codes == ["O502", "O502"]


def test_obs_accepts_guarded_idioms():
    """Module-scope pre-binding, bound-series bumps, and spans are the
    sanctioned patterns — zero findings."""
    src = (
        "from consensus_specs_tpu.obs import registry\n"
        "from consensus_specs_tpu.obs.tracing import span\n"
        "_C = registry.counter('m.pairs').labels(backend='native')\n"
        "def hot(xs):\n"
        "    _C.add(len(xs))\n"
        "    with span('m.dispatch'):\n"
        "        return work(xs)\n")
    assert _codes(obs_pass.check_source(SCOPED, src)) == []


def test_state_layer_flags_raw_extraction():
    src = (
        "import numpy as np\n"
        "from consensus_specs_tpu.utils.ssz import sequence_items\n"
        "def cols(state):\n"
        "    items = sequence_items(state.balances)\n"
        "    return np.fromiter(sequence_items(state.balances),\n"
        "                       dtype=np.uint64, count=len(items))\n")
    assert _codes(state_layer.check_source(SCOPED, src)) == ["S601"]


def test_state_layer_flags_two_line_extraction():
    """The historical shape the pass exists to ban: bind the walk to a
    name, fromiter over the name (exactly what the pre-store
    ``validator_columns`` did) — must fire like the nested one-liner."""
    src = (
        "import numpy as np\n"
        "from consensus_specs_tpu.utils.ssz import sequence_items\n"
        "def cols(state):\n"
        "    items = sequence_items(state.balances)\n"
        "    return np.fromiter(items, dtype=np.uint64, count=len(items))\n")
    findings = state_layer.check_source(SCOPED, src)
    assert _codes(findings) == ["S601"]
    assert findings[0].line == 5      # anchored at the fromiter


def test_state_layer_accepts_store_access():
    """Reading through the StateArrays store (and non-extraction
    fromiter uses) is the sanctioned pattern — zero findings."""
    src = (
        "import numpy as np\n"
        "from consensus_specs_tpu.state import arrays as state_arrays\n"
        "def cols(state, indices):\n"
        "    registry = state_arrays.registry_of(state)\n"
        "    mask = np.fromiter(indices, dtype=np.int64)\n"
        "    return registry, mask\n")
    assert state_layer.check_source(SCOPED, src) == []


def test_state_layer_flags_forkchoice_raw_imports():
    src = (
        "from consensus_specs_tpu.utils.ssz import (\n"
        "    hash_tree_root, sequence_items, replace_basic_items)\n")
    codes = _codes(state_layer.check_source(
        "consensus_specs_tpu/forkchoice/engine.py", src))
    assert codes == ["S602", "S602"]
    # the same import outside forkchoice/ is fine (write-back plumbing)
    assert state_layer.check_source(SCOPED, src) == []


def test_state_layer_out_of_scope_and_noqa():
    src = (
        "import numpy as np\n"
        "def f(seq):\n"
        "    return np.fromiter(sequence_items(seq), dtype=np.uint64)\n")
    assert state_layer.check_source(
        "consensus_specs_tpu/state/arrays.py", src) == []
    assert state_layer.check_source("tests/test_x.py", src) == []
    suppressed_src = src.replace(
        "dtype=np.uint64)", "dtype=np.uint64)  # noqa: S601")
    findings = state_layer.check_source(SCOPED, suppressed_src)
    lines = suppressed_src.split("\n")
    assert findings, "S601 must still fire so the noqa has something " \
                     "to suppress (empty findings would pass vacuously)"
    assert all(suppressed(f, lines) for f in findings)


def test_obs_out_of_scope_files_ignored():
    src = "import time\ndef f():\n    return time.perf_counter()\n"
    assert obs_pass.check_source("benchmarks/bench_all.py", src) == []
    assert obs_pass.check_source(
        "consensus_specs_tpu/obs/tracing.py", src) == []


def test_obs_noqa_suppression():
    src = (
        "import time\n"
        "def cold_build():\n"
        "    t0 = time.perf_counter()  # noqa: O501\n"
        "    return t0\n")
    findings = obs_pass.check_source(SCOPED, src)
    lines = src.splitlines()
    assert [f for f in findings if not suppressed(f, lines)] == []


ENGINE = "consensus_specs_tpu/serving/pipeline.py"   # engine scope


def test_obs_flags_span_outside_with():
    """O503: a hand-entered span leaks its frame on any exception
    between enter and exit."""
    src = (
        "from consensus_specs_tpu.obs.tracing import span\n"
        "def f(xs):\n"
        "    s = span('engine.work')\n"
        "    s.__enter__()\n"
        "    work(xs)\n"
        "    s.__exit__(None, None, None)\n")
    findings = obs_pass.check_source(ENGINE, src)
    assert _codes(findings) == ["O503"]
    assert findings[0].line == 3


def test_obs_accepts_with_span_and_manual_finally():
    """The with-item shape (including multi-item withs) and the
    try/finally-__exit__ shape are both sanctioned — zero findings."""
    src = (
        "from consensus_specs_tpu.obs import tracing\n"
        "from consensus_specs_tpu.obs.tracing import span\n"
        "def g(xs, ctx):\n"
        "    with tracing.adopt_context(ctx), \\\n"
        "            tracing.span('engine.flush'):\n"
        "        work(xs)\n"
        "    with span('engine.other'):\n"
        "        work(xs)\n"
        "def h(xs):\n"
        "    s = span('engine.manual')\n"
        "    s.__enter__()\n"
        "    try:\n"
        "        work(xs)\n"
        "    finally:\n"
        "        s.__exit__(None, None, None)\n")
    assert obs_pass.check_source(ENGINE, src) == []


def test_obs_flags_contextless_thread_submit():
    """O504: spans on a thread submitted without captured trace context
    root an [orphan thread] tree."""
    src = (
        "import threading\n"
        "def submit(win):\n"
        "    win.thread = threading.Thread(target=win.run, daemon=True)\n"
        "    win.thread.start()\n")
    findings = obs_pass.check_source(ENGINE, src)
    assert _codes(findings) == ["O504"]
    assert findings[0].line == 3


def test_obs_accepts_context_passing_thread_submit():
    """Referencing capture_context/adopt_context anywhere in the
    submitting function's subtree (the worker closure counts) clears
    O504."""
    src = (
        "import threading\n"
        "from consensus_specs_tpu.obs import tracing\n"
        "def submit(win):\n"
        "    win.ctx = tracing.capture_context()\n"
        "    def _run():\n"
        "        with tracing.adopt_context(win.ctx):\n"
        "            win.run()\n"
        "    win.thread = threading.Thread(target=_run, daemon=True)\n"
        "    win.thread.start()\n")
    assert obs_pass.check_source(ENGINE, src) == []


def test_obs_engine_scope_boundaries():
    """O503/O504 cover the engine tree but not obs/ itself, tools/, or
    hot-path-only extras; O501/O502 stay confined to HOT_PREFIXES."""
    span_src = (
        "from consensus_specs_tpu.obs.tracing import span\n"
        "def f():\n"
        "    s = span('x')\n"
        "    s.__enter__()\n")
    assert _codes(obs_pass.check_source(ENGINE, span_src)) == ["O503"]
    assert obs_pass.check_source(
        "consensus_specs_tpu/obs/http.py", span_src) == []
    assert obs_pass.check_source(
        "consensus_specs_tpu/tools/obs_report.py", span_src) == []
    assert obs_pass.check_source("tests/test_x.py", span_src) == []
    # engine scope outside HOT_PREFIXES gets O503/O504 but not O501
    clock_src = (
        "import time\n"
        "def f():\n"
        "    return time.perf_counter()\n")
    assert obs_pass.check_source(ENGINE, clock_src) == []
    assert _codes(obs_pass.check_source(SCOPED, clock_src)) == ["O501"]


# ---------------------------------------------------------------------------
# style pass / lint.py shim
# ---------------------------------------------------------------------------

def test_style_pass_keeps_legacy_checks():
    src = (
        "import os\n"
        "def f(x=[]):\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n")
    codes = _codes(style.check_source("m.py", src))
    assert {"F401", "B006", "E722"} <= set(codes)


def test_lint_shim_still_works(tmp_path):
    from consensus_specs_tpu.tools import lint
    good = tmp_path / "ok.py"
    good.write_text("x = 1\n")
    assert lint.lint_file(str(good)) == []
    assert lint.main([str(tmp_path), "--no-baseline"]) == 0
    assert list(lint.iter_py_files(str(tmp_path))) == [str(good)]


def test_lint_shim_keeps_noqa_suppression(tmp_path):
    """The historical lint_file honored # noqa on E722/B006 lines."""
    from consensus_specs_tpu.tools import lint
    target = tmp_path / "m.py"
    target.write_text(
        "try:\n"
        "    pass\n"
        "except:  # noqa\n"
        "    pass\n")
    assert lint.lint_file(str(target)) == []


# ---------------------------------------------------------------------------
# driver: noqa, baseline ratchet, real tree
# ---------------------------------------------------------------------------

def test_noqa_parsing_and_suppression():
    assert noqa_codes("x = 1") is None
    assert noqa_codes("x = 1  # noqa") == set()
    assert noqa_codes("x = 1  # noqa: U101, J203") == {"U101", "J203"}
    f = Finding("m.py", 1, "U101", "boom")
    assert suppressed(f, ["bad - code  # noqa: U101"])
    assert not suppressed(f, ["bad - code  # noqa: J203"])
    assert suppressed(f, ["bad - code  # noqa"])


def test_driver_noqa_filters_findings(tmp_path):
    root = tmp_path / "repo"
    target = root / SCOPED
    target.parent.mkdir(parents=True)
    target.write_text(
        "def f(seq):\n"
        "    balances = u64_column(seq)\n"
        "    penalties = u64_column(seq)\n"
        "    return balances - penalties  # noqa: U101\n")
    # non-vacuous: without the noqa the same tree must fail
    assert driver.run_passes(driver.Context(str(root)), {"uint64"}) == []
    target.write_text(
        "def f(seq):\n"
        "    balances = u64_column(seq)\n"
        "    penalties = u64_column(seq)\n"
        "    return balances - penalties\n")
    assert driver.run_passes(driver.Context(str(root)), {"uint64"}) != []


def test_baseline_ratchet(tmp_path):
    root = tmp_path / "repo"
    target = root / SCOPED
    target.parent.mkdir(parents=True)
    bad = ("def f(seq):\n"
           "    b = u64_column(seq)\n"
           "    p = u64_column(seq)\n"
           "    return b - p\n")
    target.write_text(bad)
    baseline = str(root / "speclint_baseline.json")

    # no baseline: the finding fails the run
    assert driver.main([str(root), "--passes", "uint64"]) == 1
    # record it, and the same tree is green
    assert driver.main([str(root), "--passes", "uint64",
                        "--write-baseline"]) == 0
    assert driver.main([str(root), "--passes", "uint64"]) == 0
    # debt grows -> ratchet fails
    target.write_text(bad + "def g(seq):\n"
                            "    b = u64_column(seq)\n"
                            "    p = u64_column(seq)\n"
                            "    return b - p\n")
    assert driver.main([str(root), "--passes", "uint64"]) == 1
    # debt paid down -> green (stale baseline is only a note)
    target.write_text("def f(seq):\n    return u64_column(seq)\n")
    assert driver.main([str(root), "--passes", "uint64"]) == 0
    with open(baseline) as f:
        assert sum(json.load(f)["counts"].values()) == 1


def test_write_baseline_with_pass_subset_preserves_other_debt(tmp_path):
    """`--passes X --write-baseline` must not delete other passes'
    recorded debt from the ratchet file."""
    root = tmp_path / "repo"
    target = root / SCOPED
    target.parent.mkdir(parents=True)
    target.write_text("def f(seq):\n"
                      "    b = u64_column(seq)\n"
                      "    p = u64_column(seq)\n"
                      "    return b - p\n")
    md = root / "specs" / "demo.md"
    md.parent.mkdir(parents=True)
    md.write_text("```python\nimport os\n```\n")
    assert driver.main([str(root), "--write-baseline"]) == 0
    assert driver.main([str(root)]) == 0
    # re-record only the uint64 pass: the M401 debt must survive
    assert driver.main([str(root), "--passes", "uint64",
                        "--write-baseline"]) == 0
    assert driver.main([str(root)]) == 0
    with open(root / "speclint_baseline.json") as f:
        counts = json.load(f)["counts"]
    assert any(k.endswith("::M401") for k in counts)
    assert any(k.endswith("::U101") for k in counts)


def test_subtree_root_warns_instead_of_silent_clean(capsys):
    """Pointing speclint at a subtree (where the repo-anchored passes
    match nothing) must say so, not just report clean."""
    assert driver.main([os.path.join(REPO, "consensus_specs_tpu"),
                        "--no-baseline", "--passes", "uint64"]) == 0
    assert "run from the repo root" in capsys.readouterr().out


def test_pass_subset_does_not_report_other_debt_as_stale(capsys):
    """`--passes uint64` must not print stale-baseline notes for the
    spec-markdown debt that legitimately did not run."""
    assert driver.main([REPO, "--passes", "uint64"]) == 0
    assert "stale" not in capsys.readouterr().out


def test_real_tree_clean_modulo_baseline():
    """`make lint`'s contract: all passes, one process, exit 0 on the
    repo with the checked-in baseline."""
    assert driver.main([REPO]) == 0


def test_real_tree_baseline_has_no_code_findings():
    """The checked-in debt is all in the reference spec markdown; the
    python tree itself must lint clean."""
    with open(os.path.join(REPO, "speclint_baseline.json")) as f:
        counts = json.load(f)["counts"]
    assert counts, "baseline unexpectedly empty"
    for key in counts:
        assert key.startswith("specs/"), f"code debt crept in: {key}"


# ---------------------------------------------------------------------------
# counted-fallback pass (R7xx)
# ---------------------------------------------------------------------------

def test_fallbacks_flags_uncounted_fallback_catch():
    """R701: absorbing the guard signal without booking the trip is a
    silent fallback — the exact failure mode the adversarial harness
    hunts dynamically."""
    src = (
        "def try_fast(spec, state):\n"
        "    try:\n"
        "        kernel(state)\n"
        "    except _Fallback:\n"
        "        return False\n"
        "    return True\n")
    findings = fallbacks.check_source(SCOPED, src)
    assert _codes(findings) == ["R701"]
    assert findings[0].line == 4      # anchored at the handler


def test_fallbacks_flags_uncounted_injected_fault():
    src = (
        "from consensus_specs_tpu import faults\n"
        "def entry(state):\n"
        "    try:\n"
        "        fast(state)\n"
        "    except (ValueError, faults.InjectedFault):\n"
        "        slow(state)\n")
    assert _codes(fallbacks.check_source(SCOPED, src)) == ["R701"]


def test_fallbacks_accepts_counted_handler():
    """Routing through count_fallback discharges R701 — anywhere in the
    function, since the BLS flush defers counting past the handler."""
    src = (
        "from consensus_specs_tpu import faults\n"
        "def try_fast(spec, state):\n"
        "    injected = None\n"
        "    try:\n"
        "        kernel(state)\n"
        "    except (_Fallback, faults.InjectedFault) as exc:\n"
        "        injected = exc\n"
        "    faults.count_fallback(_SERIES, injected)\n"
        "    return injected is None\n")
    assert fallbacks.check_source(SCOPED, src) == []


def test_fallbacks_flags_baseexception_swallow():
    """R702: a BaseException (or bare) catch-all with no raise defeats
    the InjectedFault-escapes-catch-alls design."""
    src = (
        "def run(case):\n"
        "    try:\n"
        "        case()\n"
        "    except BaseException:\n"
        "        return 'error'\n")
    assert _codes(fallbacks.check_source(SCOPED, src)) == ["R702"]
    bare = src.replace("except BaseException:", "except:")
    assert _codes(fallbacks.check_source(SCOPED, bare)) == ["R702"]


def test_fallbacks_accepts_reraising_baseexception():
    """The gen_runner shape: classify, then re-raise — not a swallow."""
    src = (
        "def run(case):\n"
        "    try:\n"
        "        case()\n"
        "    except BaseException as exc:\n"
        "        if type(exc).__name__ == 'Skipped':\n"
        "            return 'skipped'\n"
        "        raise\n")
    assert fallbacks.check_source(SCOPED, src) == []


def test_fallbacks_scope_and_noqa():
    uncounted = (
        "def f(state):\n"
        "    try:\n"
        "        g(state)\n"
        "    except _Fallback:\n"
        "        pass\n")
    swallow = (
        "def f(case):\n"
        "    try:\n"
        "        case()\n"
        "    except BaseException:\n"
        "        pass\n")
    # gen/ and sim/ are R702-only layers: faults must traverse them
    # unswallowed, but they have no engine handlers to count
    gen_path = "consensus_specs_tpu/gen/gen_runner.py"
    assert fallbacks.check_source(gen_path, uncounted) == []
    assert _codes(fallbacks.check_source(gen_path, swallow)) == ["R702"]
    # out of scope entirely
    assert fallbacks.check_source("tests/test_x.py", swallow) == []
    assert fallbacks.check_source("benchmarks/bench_all.py", swallow) == []
    # noqa suppression (driver-side), with non-empty findings to suppress
    suppressed_src = uncounted.replace(
        "except _Fallback:", "except _Fallback:  # noqa: R701")
    findings = fallbacks.check_source(SCOPED, suppressed_src)
    lines = suppressed_src.split("\n")
    assert findings, "R701 must fire so the noqa suppresses something"
    assert all(suppressed(f, lines) for f in findings)


# ---------------------------------------------------------------------------
# supervision pass (R8xx)
# ---------------------------------------------------------------------------

def test_supervision_flags_unsupervised_site():
    """R801: a dispatch wrapper calling faults.check without the
    supervisor.admit gate has no circuit breaker."""
    src = (
        "from consensus_specs_tpu import faults\n"
        "def hash_rows(rows):\n"
        "    try:\n"
        "        faults.check('merkle.dispatch')\n"
        "    except faults.InjectedFault as exc:\n"
        "        faults.count_fallback(_F, exc)\n"
        "    return rows\n")
    findings = supervision.check_source(SCOPED, src)
    assert _codes(findings) == ["R801"]
    assert findings[0].line == 4      # anchored at the check call


def test_supervision_resolves_site_variable():
    """R801 resolves the common ``site = \"...\"`` local-binding form
    on both the check and admit sides."""
    src = (
        "from consensus_specs_tpu import faults, supervisor\n"
        "def try_fast(spec, state):\n"
        "    site = 'epoch.slashings'\n"
        "    if not supervisor.admit(site):\n"
        "        return False\n"
        "    faults.check(site)\n"
        "    return True\n")
    assert supervision.check_source(SCOPED, src) == []
    unadmitted = src.replace("    if not supervisor.admit(site):\n"
                             "        return False\n", "")
    assert _codes(supervision.check_source(SCOPED, unadmitted)) == ["R801"]


def test_supervision_skips_parameter_sites():
    """A helper taking the site as a parameter (the epoch ``_audited``
    shape) is out of scope — its literal-carrying caller registers."""
    src = (
        "from consensus_specs_tpu import faults\n"
        "def _audited(spec, state, site, fast_fn):\n"
        "    faults.check(site)\n"
        "    return fast_fn(spec, state)\n")
    assert supervision.check_source(SCOPED, src) == []


def test_supervision_flags_bare_retry_loop():
    """R802: swallow-and-retry with no backoff busy-spins at full
    failure cost under a persistent fault."""
    src = (
        "def spin(fn):\n"
        "    while True:\n"
        "        try:\n"
        "            return fn()\n"
        "        except ValueError:\n"
        "            continue\n")
    findings = supervision.check_source(SCOPED, src)
    assert _codes(findings) == ["R802"]
    assert findings[0].line == 2      # anchored at the loop


def test_supervision_accepts_backoff_and_reraise_loops():
    backoff = (
        "def spin(fn):\n"
        "    while True:\n"
        "        try:\n"
        "            return fn()\n"
        "        except ValueError:\n"
        "            time.sleep(0.1)\n")
    assert supervision.check_source(SCOPED, backoff) == []
    reraise = (
        "def spin(fn):\n"
        "    while True:\n"
        "        try:\n"
        "            return fn()\n"
        "        except ValueError:\n"
        "            raise\n")
    assert supervision.check_source(SCOPED, reraise) == []


def test_supervision_scope_and_noqa():
    retry = (
        "def spin(fn):\n"
        "    while True:\n"
        "        try:\n"
        "            return fn()\n"
        "        except ValueError:\n"
        "            pass\n")
    # R802 scope is the engine packages, not the harness/test layers
    assert supervision.check_source("tests/test_x.py", retry) == []
    assert supervision.check_source(
        "consensus_specs_tpu/sim/driver.py", retry) == []
    assert _codes(supervision.check_source(
        "consensus_specs_tpu/state/arrays.py", retry)) == ["R802"]
    # noqa suppression (driver-side), non-vacuous
    noqa_src = retry.replace("    while True:",
                             "    while True:  # noqa: R802")
    findings = supervision.check_source(
        "consensus_specs_tpu/state/arrays.py", noqa_src)
    lines = noqa_src.split("\n")
    assert findings, "R802 must fire so the noqa suppresses something"
    assert all(suppressed(f, lines) for f in findings)
