"""``is_valid_terminal_pow_block`` difficulty-boundary unit tests.

Reference model:
``test/bellatrix/unittests/test_is_valid_terminal_pow_block.py``
against ``specs/bellatrix/fork-choice.md`` (block at/above TTD whose
parent is below TTD).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)

BELLATRIX_ONLY = with_phases(["bellatrix"])


def _pow_pair(spec, parent_difficulty, block_difficulty):
    parent = spec.PowBlock(block_hash=b"\x01" * 32,
                           parent_hash=b"\x00" * 32,
                           total_difficulty=parent_difficulty)
    block = spec.PowBlock(block_hash=b"\x02" * 32,
                          parent_hash=parent.block_hash,
                          total_difficulty=block_difficulty)
    return block, parent


@BELLATRIX_ONLY
@spec_state_test
def test_is_valid_terminal_pow_block_success_valid(spec, state):
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    block, parent = _pow_pair(spec, ttd - 1, ttd)
    assert spec.is_valid_terminal_pow_block(block, parent)
    yield  # unit test: no vector parts


@BELLATRIX_ONLY
@spec_state_test
def test_is_valid_terminal_pow_block_fail_before_terminal(spec, state):
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    block, parent = _pow_pair(spec, ttd - 2, ttd - 1)
    assert not spec.is_valid_terminal_pow_block(block, parent)
    yield


@BELLATRIX_ONLY
@spec_state_test
def test_is_valid_terminal_pow_block_fail_just_after_terminal(spec, state):
    """Parent already at TTD: the terminal block was one earlier."""
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    block, parent = _pow_pair(spec, ttd, ttd + 1)
    assert not spec.is_valid_terminal_pow_block(block, parent)
    yield
