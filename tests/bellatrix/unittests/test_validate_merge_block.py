"""``validate_merge_block`` unit tests.

Reference model:
``test/bellatrix/unittests/test_validate_merge_block.py`` (8 cases:
PoW-chain lookups, terminal-difficulty checks, TERMINAL_BLOCK_HASH
override + activation epoch) against
``specs/bellatrix/fork-choice.md`` ``validate_merge_block``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_config_overrides,
    expect_assertion_error,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_state_with_incomplete_transition, compute_el_block_hash,
)

BELLATRIX_ONLY = with_phases(["bellatrix"])

TB_HASH = b"\xab" * 32
TB_HASH_HEX = "0x" + TB_HASH.hex()


def _merge_block(spec, state, parent_hash):
    state = build_state_with_incomplete_transition(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    payload = block.body.execution_payload
    payload.parent_hash = parent_hash
    payload.block_hash = compute_el_block_hash(spec, payload)
    block.body.execution_payload = payload
    return block


def _with_pow_chain(spec, blocks):
    """Patch the class-level get_pow_block stub with a table lookup;
    caller must run inside the returned try/finally via _run."""
    table = {bytes(b.block_hash): b for b in blocks}
    spec.get_pow_block = lambda h: table.get(bytes(h))


def _restore(spec):
    if "get_pow_block" in spec.__dict__:
        del spec.get_pow_block


def _terminal_chain(spec, tip_hash):
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    parent = spec.PowBlock(block_hash=b"\x01" * 32,
                           parent_hash=b"\x00" * 32,
                           total_difficulty=ttd - 1)
    tip = spec.PowBlock(block_hash=tip_hash,
                        parent_hash=parent.block_hash,
                        total_difficulty=ttd)
    return tip, parent


@BELLATRIX_ONLY
@spec_state_test
def test_validate_merge_block_success(spec, state):
    block = _merge_block(spec, state, b"\xaa" * 32)
    tip, parent = _terminal_chain(spec, b"\xaa" * 32)
    _with_pow_chain(spec, [tip, parent])
    try:
        spec.validate_merge_block(block)
    finally:
        _restore(spec)
    yield


@BELLATRIX_ONLY
@spec_state_test
def test_validate_merge_block_fail_block_lookup(spec, state):
    """The payload's PoW parent is unknown to the node."""
    block = _merge_block(spec, state, b"\xaa" * 32)
    _with_pow_chain(spec, [])
    try:
        expect_assertion_error(lambda: spec.validate_merge_block(block))
    finally:
        _restore(spec)
    yield


@BELLATRIX_ONLY
@spec_state_test
def test_validate_merge_block_fail_parent_block_lookup(spec, state):
    """The PoW parent exists but ITS parent is unknown."""
    block = _merge_block(spec, state, b"\xaa" * 32)
    tip, _ = _terminal_chain(spec, b"\xaa" * 32)
    _with_pow_chain(spec, [tip])  # grandparent missing
    try:
        expect_assertion_error(lambda: spec.validate_merge_block(block))
    finally:
        _restore(spec)
    yield


@BELLATRIX_ONLY
@spec_state_test
def test_validate_merge_block_fail_after_terminal(spec, state):
    """Parent is already past TTD: the merge block anchored too late."""
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    block = _merge_block(spec, state, b"\xaa" * 32)
    parent = spec.PowBlock(block_hash=b"\x01" * 32,
                           parent_hash=b"\x00" * 32,
                           total_difficulty=ttd)
    tip = spec.PowBlock(block_hash=b"\xaa" * 32,
                        parent_hash=parent.block_hash,
                        total_difficulty=ttd + 1)
    _with_pow_chain(spec, [tip, parent])
    try:
        expect_assertion_error(lambda: spec.validate_merge_block(block))
    finally:
        _restore(spec)
    yield


@BELLATRIX_ONLY
@with_config_overrides({"TERMINAL_BLOCK_HASH": TB_HASH_HEX,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 0})
@spec_state_test
def test_validate_merge_block_tbh_override_success(spec, state):
    """With a terminal-hash override, difficulty is ignored entirely."""
    assert bytes(spec.config.TERMINAL_BLOCK_HASH) == TB_HASH
    block = _merge_block(spec, state, TB_HASH)
    # no PoW chain registered at all: the override path never looks
    spec.validate_merge_block(block)
    yield


@BELLATRIX_ONLY
@with_config_overrides({"TERMINAL_BLOCK_HASH": TB_HASH_HEX,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 0})
@spec_state_test
def test_validate_merge_block_fail_parent_hash_is_not_tbh(spec, state):
    block = _merge_block(spec, state, b"\xcd" * 32)
    expect_assertion_error(lambda: spec.validate_merge_block(block))
    yield


@BELLATRIX_ONLY
@with_config_overrides({"TERMINAL_BLOCK_HASH": TB_HASH_HEX,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 2**30})
@spec_state_test
def test_validate_merge_block_terminal_block_hash_fail_activation_not_reached(
        spec, state):
    block = _merge_block(spec, state, TB_HASH)
    expect_assertion_error(lambda: spec.validate_merge_block(block))
    yield


@BELLATRIX_ONLY
@with_config_overrides({"TERMINAL_BLOCK_HASH": TB_HASH_HEX,
                        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 2**30})
@spec_state_test
def test_validate_merge_block_fail_activation_not_reached_parent_hash_is_not_tbh(
        spec, state):
    block = _merge_block(spec, state, b"\xcd" * 32)
    expect_assertion_error(lambda: spec.validate_merge_block(block))
    yield
