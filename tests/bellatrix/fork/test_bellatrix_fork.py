"""upgrade_to_bellatrix fork tests (``specs/bellatrix/fork.md:69``)."""
from consensus_specs_tpu.forks import build_spec
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import next_epoch
from consensus_specs_tpu.utils.ssz import hash_tree_root


def run_fork_test(post_spec, pre_state):
    yield "pre", pre_state
    post_state = post_spec.upgrade_to_bellatrix(pre_state)

    for field in ("genesis_time", "genesis_validators_root", "slot",
                  "eth1_deposit_index", "justification_bits"):
        assert getattr(pre_state, field) == getattr(post_state, field)
    for field in ("block_roots", "state_roots", "historical_roots",
                  "validators", "balances", "randao_mixes", "slashings",
                  "previous_epoch_participation",
                  "current_epoch_participation", "inactivity_scores",
                  "current_sync_committee", "next_sync_committee"):
        assert hash_tree_root(getattr(pre_state, field)) == \
            hash_tree_root(getattr(post_state, field))

    assert post_state.fork.previous_version == pre_state.fork.current_version
    assert bytes(post_state.fork.current_version) == \
        bytes(post_spec.config.BELLATRIX_FORK_VERSION)

    # pre-merge header: all defaults
    assert post_state.latest_execution_payload_header == \
        post_spec.ExecutionPayloadHeader()
    assert not post_spec.is_merge_transition_complete(post_state)
    yield "post", post_state


@with_phases(["altair"])
@spec_state_test
@never_bls
def test_bellatrix_fork_basic(spec, state):
    post_spec = build_spec("bellatrix", spec.preset_name)
    yield from run_fork_test(post_spec, state)


@with_phases(["altair"])
@spec_state_test
@never_bls
def test_bellatrix_fork_next_epoch(spec, state):
    next_epoch(spec, state)
    post_spec = build_spec("bellatrix", spec.preset_name)
    yield from run_fork_test(post_spec, state)
