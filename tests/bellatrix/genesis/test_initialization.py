"""Bellatrix genesis initialization with/without a payload header.

Reference model: ``test/bellatrix/genesis/test_initialization.py``
against ``specs/bellatrix/beacon-chain.md`` Testing-section
``initialize_beacon_state_from_eth1`` (the ``execution_payload_header``
parameter decides whether the chain starts pre- or post-merge).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_test, with_phases,
)
from consensus_specs_tpu.test_infra.deposits import (
    prepare_full_genesis_deposits,
)

BELLATRIX_ONLY = with_phases(["bellatrix"])


def _genesis_deposits(spec):
    count = int(spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT)
    deposits, root, _ = prepare_full_genesis_deposits(
        spec, spec.MAX_EFFECTIVE_BALANCE, count, signed=True)
    return deposits, root


@BELLATRIX_ONLY
@spec_test
def test_initialize_pre_transition_no_param(spec):
    deposits, _ = _genesis_deposits(spec)
    state = spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, 1234567890, deposits)
    assert state.fork.current_version == spec.config.BELLATRIX_FORK_VERSION
    # default header: the merge has not happened
    assert not spec.is_merge_transition_complete(state)
    yield "state", state


@BELLATRIX_ONLY
@spec_test
def test_initialize_pre_transition_empty_payload(spec):
    deposits, _ = _genesis_deposits(spec)
    state = spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, 1234567890, deposits,
        execution_payload_header=spec.ExecutionPayloadHeader())
    assert not spec.is_merge_transition_complete(state)
    yield "state", state


@BELLATRIX_ONLY
@spec_test
def test_initialize_post_transition(spec):
    deposits, _ = _genesis_deposits(spec)
    genesis_header = spec.default_payload_header()
    state = spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, 1234567890, deposits,
        execution_payload_header=genesis_header)
    assert spec.is_merge_transition_complete(state)
    assert state.latest_execution_payload_header == genesis_header
    yield "state", state
