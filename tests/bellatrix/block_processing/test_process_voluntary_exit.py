"""Voluntary-exit signature domains across the bellatrix fork boundary.

Reference model:
``test/bellatrix/block_processing/test_process_voluntary_exit.py``
(6 cases: exits signed with current/previous/genesis fork versions for
epochs before/after the fork epoch) against phase0
``process_voluntary_exit`` + ``get_domain`` fork-version selection.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, always_bls,
)
from consensus_specs_tpu.test_infra.voluntary_exits import (
    sign_voluntary_exit, run_voluntary_exit_processing,
)
from consensus_specs_tpu.test_infra.keys import privkeys

BELLATRIX_ONLY = with_phases(["bellatrix"])


def _prepare_exit_state(spec, state, exit_epoch_offset=0):
    """Fast-forward past the shard-committee period and pin the state's
    fork to a bellatrix-boundary shape: previous=altair, current=bellatrix,
    fork epoch strictly inside the walked range."""
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    current_epoch = spec.get_current_epoch(state)
    state.fork.previous_version = spec.config.ALTAIR_FORK_VERSION
    state.fork.current_version = spec.config.BELLATRIX_FORK_VERSION
    state.fork.epoch = current_epoch - 2
    return current_epoch


def _signed_exit(spec, state, epoch, index, fork_version):
    exit_message = spec.VoluntaryExit(epoch=epoch, validator_index=index)
    return sign_voluntary_exit(spec, state, exit_message, privkeys[index],
                               fork_version=fork_version)


@BELLATRIX_ONLY
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_with_current_fork_version_is_before_fork_epoch(
        spec, state):
    """Exit epoch BEFORE the fork, signed with the CURRENT version: the
    domain must use the previous version, so this signature fails."""
    current_epoch = _prepare_exit_state(spec, state)
    signed = _signed_exit(spec, state, state.fork.epoch - 1, 0,
                          state.fork.current_version)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@BELLATRIX_ONLY
@spec_state_test
@always_bls
def test_voluntary_exit_with_current_fork_version_not_is_before_fork_epoch(
        spec, state):
    current_epoch = _prepare_exit_state(spec, state)
    assert current_epoch >= state.fork.epoch
    signed = _signed_exit(spec, state, current_epoch, 0,
                          state.fork.current_version)
    yield from run_voluntary_exit_processing(spec, state, signed)


@BELLATRIX_ONLY
@spec_state_test
@always_bls
def test_voluntary_exit_with_previous_fork_version_is_before_fork_epoch(
        spec, state):
    """Exit epoch before the fork, previous-version domain: valid."""
    _prepare_exit_state(spec, state)
    signed = _signed_exit(spec, state, state.fork.epoch - 1, 0,
                          state.fork.previous_version)
    yield from run_voluntary_exit_processing(spec, state, signed)


@BELLATRIX_ONLY
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_with_previous_fork_version_not_is_before_fork_epoch(
        spec, state):
    current_epoch = _prepare_exit_state(spec, state)
    signed = _signed_exit(spec, state, current_epoch, 0,
                          state.fork.previous_version)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@BELLATRIX_ONLY
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_with_genesis_fork_version_is_before_fork_epoch(
        spec, state):
    """The genesis version is two forks back: never the right domain."""
    _prepare_exit_state(spec, state)
    assert spec.config.GENESIS_FORK_VERSION not in (
        state.fork.previous_version, state.fork.current_version)
    signed = _signed_exit(spec, state, state.fork.epoch - 1, 0,
                          spec.config.GENESIS_FORK_VERSION)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)


@BELLATRIX_ONLY
@spec_state_test
@always_bls
def test_invalid_voluntary_exit_with_genesis_fork_version_not_is_before_fork_epoch(
        spec, state):
    current_epoch = _prepare_exit_state(spec, state)
    signed = _signed_exit(spec, state, current_epoch, 0,
                          spec.config.GENESIS_FORK_VERSION)
    yield from run_voluntary_exit_processing(spec, state, signed,
                                             valid=False)
