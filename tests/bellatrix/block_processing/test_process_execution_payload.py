"""process_execution_payload operation tests.

Reference model: ``test/bellatrix/block_processing/test_process_execution_payload.py``
against ``specs/bellatrix/beacon-chain.md:384``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, expect_assertion_error,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload, compute_el_block_hash,
    build_state_with_incomplete_transition,
    build_state_with_complete_transition,
)

EXECUTION_FORKS = ["bellatrix", "capella", "deneb"]


def run_execution_payload_processing(spec, state, body_payload, valid=True,
                                     execution_valid=True):
    """Emit pre/body/post around process_execution_payload; absent post on
    invalid (reference operations vector format)."""
    body = spec.BeaconBlockBody(execution_payload=body_payload)

    yield "pre", state
    yield "execution", {"execution_valid": execution_valid}
    yield "body", body

    class TestEngine(spec.NoopExecutionEngine):
        def verify_and_notify_new_payload(self, new_payload_request) -> bool:
            return execution_valid

    if not valid:
        expect_assertion_error(
            lambda: spec.process_execution_payload(state, body, TestEngine()))
        yield "post", None
        return

    prev_header = state.latest_execution_payload_header.copy()
    spec.process_execution_payload(state, body, TestEngine())
    yield "post", state

    assert state.latest_execution_payload_header.block_hash == \
        body_payload.block_hash
    assert state.latest_execution_payload_header != prev_header


@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_success_regular_payload(spec, state):
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload)


@with_phases(["bellatrix"])
@spec_state_test
def test_success_first_payload(spec, state):
    """Merge-transition block: empty pre header, any parent hash allowed."""
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_invalid_bad_parent_hash(spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False)


@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_invalid_bad_prev_randao(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = b"\x42" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False)


@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_invalid_future_timestamp(spec, state):
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = payload.timestamp + 1
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False)


@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_invalid_execution_engine_rejects(spec, state):
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False,
                                                execution_valid=False)


@with_phases(["bellatrix"])
@spec_state_test
def test_pre_merge_empty_payload_skipped(spec, state):
    """Before the merge an all-default payload leaves execution disabled."""
    state = build_state_with_incomplete_transition(spec, state)
    body = spec.BeaconBlockBody()
    assert not spec.is_execution_enabled(state, body)
    assert not spec.is_merge_transition_complete(state)


@with_phases(["bellatrix"])
@spec_state_test
def test_merge_transition_predicates(spec, state):
    pre = build_state_with_incomplete_transition(spec, state)
    post = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, post)
    body = spec.BeaconBlockBody(execution_payload=payload)
    assert spec.is_merge_transition_block(pre, body)
    assert spec.is_execution_enabled(pre, body)
    assert spec.is_merge_transition_complete(post)
    assert not spec.is_merge_transition_block(post, body)



@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_invalid_prev_randao_first_payload(spec, state):
    """prev_randao IS checked even on the transition payload."""
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = b"\x42" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(
        spec, state, payload, valid=False)


@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_invalid_past_timestamp(spec, state):
    state = build_state_with_complete_transition(spec, state)
    # at genesis slot the expected timestamp IS 0 — shift genesis so a
    # zero timestamp actually mismatches compute_timestamp_at_slot
    state.genesis_time = 100
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = 0
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(
        spec, state, payload, valid=False)


@with_phases(EXECUTION_FORKS)
@spec_state_test
def test_full_extra_data_round_trips(spec, state):
    """A maximum-size extra_data field is valid and lands in the header."""
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.extra_data = b"\x2a" * spec.MAX_EXTRA_DATA_BYTES
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)
    assert len(state.latest_execution_payload_header.extra_data) == \
        spec.MAX_EXTRA_DATA_BYTES
