"""``process_execution_payload`` first/regular-payload matrix.

Reference model:
``test/bellatrix/block_processing/test_process_execution_payload.py``
(26 cases: every validated field wrong on both the merge-transition
payload and a regular payload; non-validated fields randomized) against
``specs/bellatrix/beacon-chain.md`` ``process_execution_payload``.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases,
)
from consensus_specs_tpu.test_infra.block import next_slots
from consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload, compute_el_block_hash,
    build_state_with_incomplete_transition,
    build_state_with_complete_transition,
)

from tests.bellatrix.block_processing.test_process_execution_payload import (
    run_execution_payload_processing,
)

EXECUTION_FORKS = ["bellatrix", "capella", "deneb"]
BELLATRIX_ONLY = with_phases(["bellatrix"])


# -- gap slots ---------------------------------------------------------------

@BELLATRIX_ONLY
@spec_state_test
def test_success_first_payload_with_gap_slot(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    next_slots(spec, state, 2)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@BELLATRIX_ONLY
@spec_state_test
def test_success_regular_payload_with_gap_slot(spec, state):
    state = build_state_with_complete_transition(spec, state)
    next_slots(spec, state, 2)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload)


# -- engine rejection --------------------------------------------------------

@BELLATRIX_ONLY
@spec_state_test
def test_invalid_bad_execution_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(
        spec, state, payload, valid=False, execution_valid=False)


@BELLATRIX_ONLY
@spec_state_test
def test_invalid_bad_execution_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(
        spec, state, payload, valid=False, execution_valid=False)


# -- parent-hash handling on the transition payload --------------------------

@BELLATRIX_ONLY
@spec_state_test
def test_bad_parent_hash_first_payload(spec, state):
    """Pre-merge, parent_hash is unconstrained: any value is VALID."""
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = spec.Hash32(b"\x55" * 32)
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


# -- bad everything ----------------------------------------------------------

@BELLATRIX_ONLY
@spec_state_test
def test_invalid_bad_everything_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = spec.Bytes32(b"\x01" * 32)
    payload.timestamp = 0 if int(payload.timestamp) else 1
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False)


@BELLATRIX_ONLY
@spec_state_test
def test_invalid_bad_everything_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = spec.Hash32(b"\x55" * 32)
    payload.prev_randao = spec.Bytes32(b"\x01" * 32)
    payload.timestamp = 0 if int(payload.timestamp) else 1
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False)


# -- timestamps on both payload kinds ----------------------------------------

@BELLATRIX_ONLY
@spec_state_test
def test_invalid_future_timestamp_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = payload.timestamp + 1
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False)


@BELLATRIX_ONLY
@spec_state_test
def test_invalid_past_timestamp_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    state.genesis_time = 100
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = 0
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload,
                                                valid=False)


# -- non-validated fields round-trip -----------------------------------------

@BELLATRIX_ONLY
@spec_state_test
def test_non_empty_extra_data_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.extra_data = b"\x45" * 12
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)
    assert bytes(state.latest_execution_payload_header.extra_data) == \
        b"\x45" * 12


@BELLATRIX_ONLY
@spec_state_test
def test_non_empty_extra_data_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.extra_data = b"\x45" * 12
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@BELLATRIX_ONLY
@spec_state_test
def test_non_empty_transactions_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.transactions = [spec.Transaction(b"\x99" * 128)
                            for _ in range(2)]
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)
    from consensus_specs_tpu.utils.ssz import hash_tree_root
    assert state.latest_execution_payload_header.transactions_root == \
        hash_tree_root(payload.transactions)


@BELLATRIX_ONLY
@spec_state_test
def test_non_empty_transactions_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.transactions = [spec.Transaction(b"\x99" * 128)
                            for _ in range(2)]
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@BELLATRIX_ONLY
@spec_state_test
def test_zero_length_transaction_first_payload(spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.transactions = [spec.Transaction(b"")]
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@BELLATRIX_ONLY
@spec_state_test
def test_zero_length_transaction_regular_payload(spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.transactions = [spec.Transaction(b"")]
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


# -- randomized non-validated execution fields -------------------------------

def _randomize_non_validated_fields(spec, payload, rng):
    """Fields the consensus layer does NOT check: any value must ride
    through when the engine accepts, and must not mask an engine reject."""
    payload.fee_recipient = spec.ExecutionAddress(rng.randbytes(20))
    payload.state_root = spec.Bytes32(rng.randbytes(32))
    payload.receipts_root = spec.Bytes32(rng.randbytes(32))
    payload.logs_bloom = rng.randbytes(int(spec.BYTES_PER_LOGS_BLOOM))
    payload.block_number = rng.randrange(1 << 40)
    payload.gas_limit = rng.randrange(1 << 40)
    payload.gas_used = rng.randrange(1 << 40)
    payload.extra_data = rng.randbytes(rng.randrange(
        int(spec.MAX_EXTRA_DATA_BYTES)))
    payload.base_fee_per_gas = rng.randrange(1 << 64)
    payload.block_hash = compute_el_block_hash(spec, payload)


@BELLATRIX_ONLY
@spec_state_test
def test_randomized_non_validated_execution_fields_first_payload__execution_valid(
        spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    _randomize_non_validated_fields(spec, payload, Random(1111))
    yield from run_execution_payload_processing(spec, state, payload)


@BELLATRIX_ONLY
@spec_state_test
def test_randomized_non_validated_execution_fields_regular_payload__execution_valid(
        spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    _randomize_non_validated_fields(spec, payload, Random(2222))
    yield from run_execution_payload_processing(spec, state, payload)


@BELLATRIX_ONLY
@spec_state_test
def test_invalid_randomized_non_validated_execution_fields_first_payload__execution_invalid(
        spec, state):
    state = build_state_with_incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    _randomize_non_validated_fields(spec, payload, Random(3333))
    yield from run_execution_payload_processing(
        spec, state, payload, valid=False, execution_valid=False)


@BELLATRIX_ONLY
@spec_state_test
def test_invalid_randomized_non_validated_execution_fields_regular_payload__execution_invalid(
        spec, state):
    state = build_state_with_complete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    _randomize_non_validated_fields(spec, payload, Random(4444))
    yield from run_execution_payload_processing(
        spec, state, payload, valid=False, execution_valid=False)
