"""Merge-transition fork-choice tests.

Reference model: ``test/bellatrix/fork_choice/test_on_merge_block.py``
against ``specs/bellatrix/fork-choice.md:204`` (validate_merge_block).
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_state_with_incomplete_transition, build_empty_execution_payload,
    compute_el_block_hash,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, tick_and_add_block,
)


def _merge_block_setup(spec, state):
    """Pre-merge anchor + a signed merge-transition block."""
    state = build_state_with_incomplete_transition(spec, state)
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)

    # build_empty_block fills a slot-consistent payload; repoint its
    # parent at a PoW block to make this the merge-transition block
    block = build_empty_block_for_next_slot(spec, state)
    payload = block.body.execution_payload
    payload.parent_hash = b"\xaa" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    block.body.execution_payload = payload
    signed_block = state_transition_and_sign_block(spec, state.copy(), block)
    return state, store, signed_block, payload


@with_phases(["bellatrix"])
@spec_state_test
@never_bls
def test_merge_block_valid_terminal_pow(spec, state):
    state, store, signed_block, payload = _merge_block_setup(spec, state)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)

    def get_pow_block(block_hash):
        if bytes(block_hash) == bytes(payload.parent_hash):
            return spec.PowBlock(block_hash=block_hash,
                                 parent_hash=b"\xbb" * 32,
                                 total_difficulty=ttd)
        return spec.PowBlock(block_hash=block_hash,
                             parent_hash=b"\x00" * 32,
                             total_difficulty=max(0, ttd - 1))

    spec.get_pow_block = get_pow_block
    try:
        test_steps = []
        tick_and_add_block(spec, store, signed_block, test_steps)
        from consensus_specs_tpu.utils.ssz import hash_tree_root
        assert hash_tree_root(signed_block.message) in store.blocks
    finally:
        del spec.get_pow_block  # restore the class-level stub


@with_phases(["bellatrix"])
@spec_state_test
@never_bls
def test_invalid_merge_block_pow_below_ttd(spec, state):
    state, store, signed_block, payload = _merge_block_setup(spec, state)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)

    def get_pow_block(block_hash):
        # terminal difficulty NOT reached
        return spec.PowBlock(block_hash=block_hash,
                             parent_hash=b"\xbb" * 32,
                             total_difficulty=max(0, ttd - 1))

    spec.get_pow_block = get_pow_block
    try:
        test_steps = []
        tick_and_add_block(spec, store, signed_block, test_steps,
                           valid=False)
    finally:
        del spec.get_pow_block


@with_phases(["bellatrix"])
@spec_state_test
@never_bls
def test_invalid_merge_block_missing_pow_parent(spec, state):
    state, store, signed_block, payload = _merge_block_setup(spec, state)
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)

    def get_pow_block(block_hash):
        if bytes(block_hash) == bytes(payload.parent_hash):
            return spec.PowBlock(block_hash=block_hash,
                                 parent_hash=b"\xbb" * 32,
                                 total_difficulty=ttd)
        return None  # parent unavailable

    spec.get_pow_block = get_pow_block
    try:
        test_steps = []
        tick_and_add_block(spec, store, signed_block, test_steps,
                           valid=False)
    finally:
        del spec.get_pow_block
