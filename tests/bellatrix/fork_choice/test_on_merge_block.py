"""Merge-transition fork-choice tests.

Reference model: ``test/bellatrix/fork_choice/test_on_merge_block.py``
against ``specs/bellatrix/fork-choice.md:204`` (validate_merge_block).
Vector format: the fork_choice event log plus ``pow_block_<hash>`` parts
describing the PoW chain the merge block anchors to.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls, emit_part,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_state_with_incomplete_transition, compute_el_block_hash,
)
from consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store_and_block, tick_and_add_block,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root


def _merge_block_setup(spec, state):
    """Pre-merge anchor + a signed merge-transition block."""
    state = build_state_with_incomplete_transition(spec, state)
    store, anchor_block = get_genesis_forkchoice_store_and_block(spec, state)

    # build_empty_block fills a slot-consistent payload; repoint its
    # parent at a PoW block to make this the merge-transition block
    block = build_empty_block_for_next_slot(spec, state)
    payload = block.body.execution_payload
    payload.parent_hash = b"\xaa" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    block.body.execution_payload = payload
    signed_block = state_transition_and_sign_block(spec, state.copy(), block)
    return state, store, signed_block, payload


def _register_pow_chain(pow_blocks, test_steps):
    """Emit the PoW blocks as vector parts + steps, and return the lookup
    the spec's get_pow_block stub will serve."""
    table = {}
    for pb in pow_blocks:
        name = "pow_block_0x" + bytes(pb.block_hash).hex()
        emit_part(name, pb)
        test_steps.append({"pow_block": name})
        table[bytes(pb.block_hash)] = pb
    return table


def _run_merge_block_case(spec, state, pow_blocks, valid):
    state, store, signed_block, payload = _merge_block_setup(spec, state)
    test_steps = []
    table = _register_pow_chain(pow_blocks(spec, payload), test_steps)
    spec.get_pow_block = lambda h: table.get(bytes(h))
    try:
        tick_and_add_block(spec, store, signed_block, test_steps,
                           valid=valid)
        if valid:
            assert hash_tree_root(signed_block.message) in store.blocks
    finally:
        del spec.get_pow_block  # restore the class-level stub
    yield "steps", test_steps


@with_phases(["bellatrix"])
@spec_state_test
@never_bls
def test_merge_block_valid_terminal_pow(spec, state):
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)

    def pow_blocks(spec, payload):
        return [
            spec.PowBlock(block_hash=payload.parent_hash,
                           parent_hash=b"\xbb" * 32,
                           total_difficulty=ttd),
            spec.PowBlock(block_hash=b"\xbb" * 32,
                           parent_hash=b"\x00" * 32,
                           total_difficulty=max(0, ttd - 1)),
        ]
    yield from _run_merge_block_case(spec, state, pow_blocks, True)


@with_phases(["bellatrix"])
@spec_state_test
@never_bls
def test_invalid_merge_block_pow_below_ttd(spec, state):
    """Terminal difficulty NOT reached by the payload's PoW parent."""
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)

    def pow_blocks(spec, payload):
        return [
            spec.PowBlock(block_hash=payload.parent_hash,
                           parent_hash=b"\xbb" * 32,
                           total_difficulty=max(0, ttd - 1)),
            spec.PowBlock(block_hash=b"\xbb" * 32,
                           parent_hash=b"\x00" * 32,
                           total_difficulty=max(0, ttd - 2)),
        ]
    yield from _run_merge_block_case(spec, state, pow_blocks, False)


@with_phases(["bellatrix"])
@spec_state_test
@never_bls
def test_invalid_merge_block_missing_pow_parent(spec, state):
    """The PoW parent of the terminal block is unavailable."""
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)

    def pow_blocks(spec, payload):
        return [
            spec.PowBlock(block_hash=payload.parent_hash,
                           parent_hash=b"\xbb" * 32,
                           total_difficulty=ttd),
        ]
    yield from _run_merge_block_case(spec, state, pow_blocks, False)
