"""Optimistic-sync rule tests (``sync/optimistic.md``).

Reference model: ``test/bellatrix/sync/test_optimistic.py``.
"""
from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, never_bls, pytest_only, emit_part,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_state_with_incomplete_transition,
)
from consensus_specs_tpu.utils.ssz import hash_tree_root

EXECUTION_FORKS = ["bellatrix", "capella", "deneb"]


def _chain(spec, state, n):
    blocks = []
    for _ in range(n):
        block = build_empty_block_for_next_slot(spec, state)
        state_transition_and_sign_block(spec, state, block)
        blocks.append(block)
    return blocks


@pytest_only
@with_phases(EXECUTION_FORKS)
@spec_state_test
@never_bls
def test_optimistic_store_and_ancestor_walk(spec, state):
    anchor_state = state.copy()
    anchor_block = spec.BeaconBlock(state_root=hash_tree_root(anchor_state))
    opt_store = spec.get_optimistic_store(anchor_state, anchor_block)

    blocks = _chain(spec, state, 3)
    for b in blocks:
        opt_store.blocks[bytes(hash_tree_root(b))] = b
    # mark the last two optimistic
    for b in blocks[1:]:
        opt_store.optimistic_roots.add(bytes(hash_tree_root(b)))

    assert not spec.is_optimistic(opt_store, blocks[0])
    assert spec.is_optimistic(opt_store, blocks[2])
    # ancestor walk stops at the first verified block
    assert spec.latest_verified_ancestor(opt_store, blocks[2]) == blocks[0]


@pytest_only
@with_phases(["bellatrix"])
@spec_state_test
@never_bls
def test_optimistic_candidate_rules(spec, state):
    pre_merge = build_state_with_incomplete_transition(spec, state)
    anchor_block = spec.BeaconBlock(state_root=hash_tree_root(pre_merge))
    opt_store = spec.get_optimistic_store(pre_merge, anchor_block)

    # parent without execution payload: only old blocks qualify
    parent = spec.BeaconBlock(slot=1)
    child = spec.BeaconBlock(slot=2, parent_root=hash_tree_root(parent))
    opt_store.blocks[bytes(hash_tree_root(parent))] = parent
    assert not spec.is_execution_block(parent)
    assert not spec.is_optimistic_candidate_block(
        opt_store, current_slot=child.slot + 1, block=child)
    assert spec.is_optimistic_candidate_block(
        opt_store,
        current_slot=child.slot + spec.SAFE_SLOTS_TO_IMPORT_OPTIMISTICALLY,
        block=child)

    # parent with an execution payload: always a candidate
    exec_parent = spec.BeaconBlock(slot=1)
    exec_parent.body.execution_payload.block_hash = b"\x01" * 32
    child2 = spec.BeaconBlock(slot=2, parent_root=hash_tree_root(exec_parent))
    opt_store.blocks[bytes(hash_tree_root(exec_parent))] = exec_parent
    assert spec.is_execution_block(exec_parent)
    assert spec.is_optimistic_candidate_block(
        opt_store, current_slot=child2.slot + 1, block=child2)


@with_phases(EXECUTION_FORKS)
@spec_state_test
@never_bls
def test_optimistic_import_then_payload_verdicts(spec, state):
    """Event-log scenario for the ``sync`` vector format: a chain imported
    optimistically, then engine verdicts — VALID on the middle block
    verifies it and its ancestors; INVALIDATED on its child prunes the
    whole descendant subtree."""
    anchor_state = state.copy()
    anchor_block = spec.BeaconBlock(state_root=hash_tree_root(anchor_state))
    emit_part("anchor_state", anchor_state)
    emit_part("anchor_block", anchor_block)
    opt_store = spec.get_optimistic_store(anchor_state, anchor_block)

    steps = []
    blocks = _chain(spec, state, 4)
    roots = [bytes(hash_tree_root(b)) for b in blocks]
    for b, r in zip(blocks, roots):
        name = "block_0x" + r.hex()
        emit_part(name, b)
        spec.import_optimistic_block(opt_store, b)
        steps.append({"block": name, "payload_status": "SYNCING"})
        assert spec.is_optimistic(opt_store, b)

    # the engine validates block[1]: it and block[0] become verified
    spec.on_payload_status(opt_store, roots[1], valid=True)
    steps.append({"payload_status_update": "0x" + roots[1].hex(),
                  "status": "VALID"})
    assert not spec.is_optimistic(opt_store, blocks[0])
    assert not spec.is_optimistic(opt_store, blocks[1])
    assert spec.is_optimistic(opt_store, blocks[2])
    assert spec.latest_verified_ancestor(opt_store, blocks[3]) == blocks[1]
    steps.append({"checks": {
        "optimistic_roots": ["0x" + r.hex() for r in roots[2:]],
        "latest_verified_ancestor": "0x" + roots[1].hex()}})

    # the engine invalidates block[2]: it and block[3] are pruned
    spec.on_payload_status(opt_store, roots[2], valid=False)
    steps.append({"payload_status_update": "0x" + roots[2].hex(),
                  "status": "INVALIDATED"})
    assert roots[2] not in opt_store.blocks
    assert roots[3] not in opt_store.blocks
    assert not opt_store.optimistic_roots
    steps.append({"checks": {"optimistic_roots": [],
                             "pruned": ["0x" + roots[2].hex(),
                                        "0x" + roots[3].hex()]}})
    yield "steps", steps
