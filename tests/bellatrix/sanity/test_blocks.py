"""Bellatrix whole-block sanity transitions.

Reference model: ``test/bellatrix/sanity/test_blocks.py`` (empty
no-transaction block, randomized payload, execution-disabled block)
against ``specs/bellatrix/beacon-chain.md`` ``process_block``.
"""
from random import Random

from consensus_specs_tpu.test_infra.context import (
    spec_state_test, with_phases, with_all_phases_from,
)
from consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot, state_transition_and_sign_block,
)
from consensus_specs_tpu.test_infra.execution_payload import (
    build_state_with_incomplete_transition, compute_el_block_hash,
)

BELLATRIX_ONLY = with_phases(["bellatrix"])
with_bellatrix_and_later = with_all_phases_from("bellatrix")


@with_bellatrix_and_later
@spec_state_test
def test_empty_block_transition_no_tx(spec, state):
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    assert len(block.body.execution_payload.transactions) == 0
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert state.latest_execution_payload_header.block_hash == \
        block.body.execution_payload.block_hash


@BELLATRIX_ONLY
@spec_state_test
def test_block_transition_randomized_payload(spec, state):
    rng = Random(7070)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    payload = block.body.execution_payload
    payload.fee_recipient = spec.ExecutionAddress(rng.randbytes(20))
    payload.gas_limit = rng.randrange(1 << 40)
    payload.gas_used = rng.randrange(1 << 40)
    payload.transactions = [
        spec.Transaction(rng.randbytes(rng.randrange(1, 256)))
        for _ in range(rng.randrange(1, 5))]
    payload.block_hash = compute_el_block_hash(spec, payload)
    block.body.execution_payload = payload
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state


@BELLATRIX_ONLY
@spec_state_test
def test_is_execution_enabled_false(spec, state):
    """Pre-merge block with the default payload: execution stays off."""
    state = build_state_with_incomplete_transition(spec, state)
    yield "pre", state
    block = build_empty_block_for_next_slot(spec, state)
    block.body.execution_payload = spec.ExecutionPayload()
    assert not spec.is_execution_enabled(state, block.body)
    signed_block = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed_block]
    yield "post", state
    assert not spec.is_merge_transition_complete(state)
